#!/usr/bin/env python
"""Headline benchmark: Llama causal-LM training MFU on one TPU chip.

Prints ONE JSON line:
  {"metric": "llama_train_mfu", "value": <MFU>, "unit": "mfu_fraction",
   "vs_baseline": <MFU / 0.40 north-star>}

Config scales to the 16 GiB HBM of a single v5e: llama-350m, seq 2048,
bf16 params + fp32 master weights + AdamW, flash-attention path, donated
compiled step (the same TrainStep users run).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp


# MFU accounting (peak table + flops/token formula) lives in
# paddle_tpu/observability/mfu.py — ONE source shared with the runtime
# StepMonitor, so bench numbers and telemetry step events agree by
# construction.  Thin re-exports keep the historical bench.py surface.

def peak_flops() -> float:
    from paddle_tpu.observability.mfu import peak_flops as _pf
    return _pf()


def provenance(fused_ops="auto") -> dict:
    """Attribution block stamped into every bench JSON so
    tools/bench_compare.py trajectories can say WHICH code/toolchain
    produced each point (r01–r05 predate this; the compare tool
    backfills).  Never fatal — a missing .git dir just yields null."""
    git_sha = None
    try:
        import subprocess
        git_sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass
    return {"git_sha": git_sha,
            "jax": getattr(jax, "__version__", None),
            "backend": jax.default_backend(),
            "device": getattr(jax.devices()[0], "device_kind", "cpu"),
            "fused": fused_ops}


def measure(preset, batch_size, seq_len, steps, windows, remat=False,
            loss_chunks=1, fuse=False, remat_layers=None,
            fused_ops="auto"):
    """One full measurement: build model+step, warm up, time `windows`
    independent windows of `steps` steps.  Returns (mfu, stats dict).

    ``fused_ops`` routes the model through the fused-kernel library
    (docs/KERNELS.md): "on"/"off"/"auto" — the one-flag MFU A/B
    (``--fused`` on the CLI).  ``fuse`` is the older trace-time
    weight-concat knob, kept for tune_sweep compatibility."""
    import gc

    import paddle_tpu as pt
    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import causal_lm_loss, llama

    pt.seed(0)
    model = llama(preset, max_position_embeddings=seq_len,
                  use_recompute=remat, loss_seq_chunks=loss_chunks,
                  fuse_qkv_mlp=fuse, recompute_num_layers=remat_layers,
                  fused_ops=fused_ops)
    cfg = model.cfg
    opt = optimizer.AdamW(learning_rate=3e-4, weight_decay=0.1,
                          grad_clip=nn.ClipGradByGlobalNorm(1.0),
                          parameters=model.parameters())
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    step = TrainStep(model, causal_lm_loss, opt)
    state = step.init_state(seed=0)

    ids = jax.random.randint(jax.random.key(0), (batch_size, seq_len), 0,
                             cfg.vocab_size)
    batch = {"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)}

    # warmup / compile (float() forces a device->host transfer — under the
    # axon relay block_until_ready alone does not synchronise)
    state, m = step(state, batch)
    _ = float(m["loss"])

    # measure N independent windows; report the BEST but record ALL window
    # values so a transient relay stall is visible in the artifact, not
    # silently discarded (VERDICT r2 weak #5)
    window_dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, batch)
        _ = float(m["loss"])
        window_dts.append(time.perf_counter() - t0)
    dt = min(window_dts)

    steps_per_sec = steps / dt
    tokens_per_sec = steps_per_sec * batch_size * seq_len
    n_params = cfg.num_params()
    # causal-attention-aware model flops per token: 6N + 6*L*h*T (the
    # shared accounting in observability/mfu.py)
    from paddle_tpu.observability.mfu import causal_lm_flops_per_token
    flops_per_token = causal_lm_flops_per_token(
        n_params, cfg.num_hidden_layers, cfg.hidden_size, seq_len)
    mfu = tokens_per_sec * flops_per_token / peak_flops()
    stats = {
        "preset": preset, "params": n_params,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "ms_per_step": round(1000 * dt / steps, 2),
        "window_ms_per_step": [round(1000 * w / steps, 2)
                               for w in window_dts],
        "batch": batch_size, "seq": seq_len,
        "loss": float(m["loss"]),
        "fused": fused_ops,
    }
    # free this model's device buffers before a follow-up measurement
    del state, step, model, opt, batch, ids
    gc.collect()
    return mfu, stats


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    # the one-flag fused-kernel A/B (docs/KERNELS.md): --fused off is
    # the pre-fusion baseline, --fused on forces the fused entry points
    # everywhere, auto (default) fuses where a kernel serves, mega
    # additionally collapses each cached decoder layer into the
    # one-dispatch megakernel ("Decode megakernel").  Env
    # PDTPU_BENCH_FUSED_OPS backs the flag for driver scripts.
    ap.add_argument("--fused", choices=("on", "off", "auto", "mega"),
                    default=os.environ.get("PDTPU_BENCH_FUSED_OPS",
                                           "auto"))
    args, _ = ap.parse_known_args()
    fused_ops = args.fused
    if fused_ops not in ("on", "off", "auto", "mega"):
        # argparse only validates choices for EXPLICIT flags — a typo'd
        # env default would otherwise die mid-trace, long after telemetry
        # already recorded the bogus mode
        ap.error(f"PDTPU_BENCH_FUSED_OPS={fused_ops!r}: expected "
                 "on|off|auto|mega")
    on_tpu = jax.default_backend() != "cpu"
    preset = os.environ.get("PDTPU_BENCH_PRESET",
                            "llama-350m" if on_tpu else "tiny")
    # telemetry sidecar: every bench run also produces a runtime-schema
    # JSONL stream (step/compile/metrics events — docs/OBSERVABILITY.md),
    # so BENCH_r*.json and production telemetry share one vocabulary.
    # Set PDTPU_BENCH_TELEMETRY="" to disable.
    tel = None
    tel_path = os.environ.get("PDTPU_BENCH_TELEMETRY",
                              "bench_telemetry.jsonl")
    if tel_path:
        from paddle_tpu import observability as obs
        tel = obs.enable(jsonl_path=tel_path)
        tel.emit({"event": "run_meta", "kind": "bench", "preset": preset,
                  "backend": jax.default_backend(), "fused": fused_ops,
                  "device": getattr(jax.devices()[0], "device_kind", "cpu")})
    # defaults picked by on-chip sweep (v5e, 2026-07-30): bs4/seq2048 with
    # recompute OFF fits 16 GiB HBM and lands 0.42 MFU; remat ON costs an
    # uncredited extra forward (0.32), bs8 no-remat OOMs by 1.7 GiB
    batch_size = int(os.environ.get("PDTPU_BENCH_BATCH", 4 if on_tpu else 2))
    seq_len = int(os.environ.get("PDTPU_BENCH_SEQ", 2048 if on_tpu else 64))
    # 60 steps ≈ 15s of steady-state (r2: widened from 40 — headline
    # run-to-run spread was ~0.002 MFU at 40)
    steps = int(os.environ.get("PDTPU_BENCH_STEPS", 60 if on_tpu else 3))

    remat = os.environ.get("PDTPU_BENCH_REMAT", "0") == "1"
    # seq-chunked rematerialized vocab CE skips the [B,S,V] logits
    # materialization; it makes bs8 fit (bs8 is slower end-to-end, so the
    # default stays bs4 + unchunked: 0.437 vs 0.435 chunked, sweep
    # 2026-07-30) — the knob exists for memory-tight configs
    loss_chunks = int(os.environ.get("PDTPU_BENCH_LOSS_CHUNKS", 1))
    fuse = os.environ.get("PDTPU_BENCH_FUSE", "0") == "1"
    windows = max(1, int(os.environ.get("PDTPU_BENCH_WINDOWS",
                                        2 if on_tpu else 1)))

    mfu, stats = measure(preset, batch_size, seq_len, steps, windows,
                         remat=remat, loss_chunks=loss_chunks, fuse=fuse,
                         fused_ops=fused_ops)
    extra = {**stats,
             "backend": jax.default_backend(),
             "device": getattr(jax.devices()[0], "device_kind", "cpu"),
             "provenance": provenance(fused_ops)}

    def extra_point(prefix, *args, keys=("ms_per_step",
                                         "window_ms_per_step",
                                         "tokens_per_sec_per_chip"), **kw):
        # secondary measurement: never let it kill the already-measured
        # headline JSON (an unvalidated env geometry, e.g. seq 4096, may
        # OOM the memory-tightest config)
        try:
            p_mfu, p_stats = measure(*args, **kw)
        except Exception as e:  # noqa: BLE001 — report, don't die
            extra[f"{prefix}_error"] = f"{type(e).__name__}: {e}"[:300]
            return
        extra[f"{prefix}_mfu"] = round(p_mfu, 4)
        for k in keys:
            extra[f"{prefix}_{k}"] = p_stats[k]

    # north-star attention geometry (head_dim 128, the 7B shape): measured
    # in the same run so the driver artifact carries it, not just docs
    # (VERDICT r2 weak #1 / next-round #4)
    if on_tpu and os.environ.get("PDTPU_BENCH_HD128", "1") == "1":
        extra_point("hd128", "llama-350m-hd128", batch_size, seq_len,
                    max(20, steps // 2), windows, fused_ops=fused_ops)

    # first measured point above 350M: llama-1b (h=2048, 16×d128, 0.94B
    # params).  fp32 master + AdamW moments alone are 10.5 GiB of the
    # 16 GiB HBM, so the honest single-chip config needs remat; the
    # on-chip sweep (2026-07-31) picked bs4 + partial remat of 12/16
    # layers (RL=8 OOMs, full remat 0.559, RL=12 0.564).  MFU is credited
    # at 6N — no recompute credit — so this carries a ~22% remat tax the
    # sharded-moment multi-chip config does not pay (docs/BENCH.md §1b).
    if on_tpu and os.environ.get("PDTPU_BENCH_LLAMA1B", "1") == "1":
        extra_point("llama1b", "llama-1b", 4, seq_len,
                    max(20, steps // 2), windows,
                    keys=("ms_per_step", "window_ms_per_step",
                          "tokens_per_sec_per_chip", "params"),
                    remat=True, remat_layers=12, fused_ops=fused_ops)

    # serving decode at the recommended quantized point (int8 weights +
    # int8 KV — docs/BENCH.md "stacked serving quantization"), slope
    # protocol so relay RTT cancels; non-fatal like the other extras
    if on_tpu and os.environ.get("PDTPU_BENCH_DECODE", "1") == "1":
        try:
            import contextlib
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            from decode_bench import bench_generate
            with contextlib.redirect_stdout(sys.stderr):  # ONE-JSON-line contract
                # full decode_bench protocol (512-token slope, 3 repeats):
                # shorter windows measured 4x-impossible throughputs
                # through the relay's RTT jitter
                r = bench_generate(batch=1, n_lo=16, n_hi=528, repeats=3,
                                   kv_cache_dtype="int8", weight_quant="int8")
            extra["decode_bs1_int8w_int8kv_tok_s"] = r["tokens_per_sec"]
            extra["decode_bs1_ms_per_token"] = r["ms_per_token"]
        except Exception as e:  # noqa: BLE001
            extra["decode_error"] = f"{type(e).__name__}: {e}"[:300]

    # aggregate continuous-batching serving throughput (serving.Engine
    # over the paged KV pools — docs/SERVING.md): mixed prompt lengths
    # churning through max_batch=8 slots.  Runs on CPU too (tiny preset,
    # small budget) so the metric's PLUMBING is exercised everywhere;
    # the numbers that matter come from TPU rounds.  Non-fatal like the
    # other extras.
    if os.environ.get("PDTPU_BENCH_SERVE", "1") == "1":
        import contextlib
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        try:
            from decode_bench import bench_serve
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve(max_batch=8, kv_cache_dtype="int8")
                else:
                    r = bench_serve(preset="tiny", max_batch=4,
                                    n_requests=6, max_new=8,
                                    prompt_lens=(5, 12, 9, 17),
                                    page_size=8, repeats=1)
            extra["serve_bs8_tok_s" if on_tpu else "serve_cpu_tok_s"] = \
                r["agg_tokens_per_sec"]
            extra["serve_detail"] = {k: r[k] for k in
                                     ("max_batch", "requests", "kv",
                                      "max_new_tokens", "gen_tokens",
                                      "wall_s")}
        except Exception as e:  # noqa: BLE001
            extra["serve_error"] = f"{type(e).__name__}: {e}"[:300]

        # shared-prefix / bursty-admission serving: millions of users
        # behind one system prompt — prefix-cache hit rate must be > 0
        # and TTFT p95 under burst load is the latency headline
        # (docs/SERVING.md).  Same CPU-plumbing / TPU-numbers split and
        # non-fatality as the churn workload above.
        try:
            from decode_bench import bench_serve_prefix
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve_prefix(max_batch=8,
                                           kv_cache_dtype="int8")
                else:
                    r = bench_serve_prefix(preset="tiny", max_batch=2,
                                           n_requests=4, shared_prefix=16,
                                           tail_lens=(4, 9), max_new=6,
                                           page_size=8, prefill_chunk=8)
            pre = "serve_prefix" if on_tpu else "serve_prefix_cpu"
            extra[f"{pre}_ttft_p95_ms"] = r["warm_ttft_p95_ms"]
            extra[f"{pre}_tok_s"] = r["warm_agg_tokens_per_sec"]
            extra[f"{pre}_hit_rate"] = r["prefix_hit_rate"]
            if tel is not None and (r.get("cold_trace")
                                    or r.get("warm_trace")):
                # sampled per-request phase breakdown (one cold, one
                # prefix-warm) into the sidecar: BENCH rounds carry
                # attribution, not just aggregates (OBSERVABILITY.md)
                tel.emit({"event": "serve_trace_sample", "row": pre,
                          "cold": r.get("cold_trace"),
                          "warm": r.get("warm_trace")})
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("requests", "shared_prefix",
                                  "prefill_chunk", "cold_ttft_p95_ms",
                                  "cold_agg_tokens_per_sec",
                                  "warm_prefix_hits", "cow_copies")}
        except Exception as e:  # noqa: BLE001
            extra["serve_prefix_error"] = f"{type(e).__name__}: {e}"[:300]

        # overload: offered load > capacity through the bounded front
        # door (docs/SERVING.md "Front door") — goodput tok/s, shed
        # rate, and TTFT p95 for the traffic that WAS admitted.  Same
        # CPU-plumbing / TPU-numbers split and non-fatality as above.
        try:
            from decode_bench import bench_serve_burst
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve_burst(max_batch=8,
                                          kv_cache_dtype="int8")
                else:
                    r = bench_serve_burst(preset="tiny", max_batch=2,
                                          offered=8, max_queue_depth=3,
                                          prompt_lens=(5, 11, 8),
                                          max_new=6, page_size=8)
            pre = "serve_burst" if on_tpu else "serve_burst_cpu"
            extra[f"{pre}_goodput_tok_s"] = r["goodput_tok_s"]
            extra[f"{pre}_shed_rate"] = r["shed_rate"]
            extra[f"{pre}_ttft_p95_ms"] = r["admitted_ttft_p95_ms"]
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("offered", "admitted", "shed",
                                  "max_queue_depth", "gen_tokens",
                                  "wall_s", "admitted_ttft_p50_ms")}
        except Exception as e:  # noqa: BLE001
            extra["serve_burst_error"] = f"{type(e).__name__}: {e}"[:300]

        # speculative decoding (docs/SERVING.md "Speculative
        # decoding"): n-gram self-drafting through the one compiled
        # verify step on a repetitive (code/templated) workload —
        # acceptance rate and tok/s vs the spec-off engine.  Same
        # CPU-plumbing / TPU-numbers split and non-fatality as above.
        try:
            from decode_bench import bench_serve_spec
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve_spec(max_batch=8,
                                         kv_cache_dtype="int8")
                else:
                    r = bench_serve_spec(preset="tiny", max_batch=4,
                                         n_requests=6, max_new=24,
                                         motif_len=6, motif_reps=3,
                                         draft_depth=4, page_size=8)
            pre = "serve_spec" if on_tpu else "serve_spec_cpu"
            extra[f"{pre}_tok_s"] = r["agg_tokens_per_sec"]
            extra[f"{pre}_accept_rate"] = r["accept_rate"]
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("draft_depth", "proposed", "accepted",
                                  "tokens_per_verify_step", "steps",
                                  "base_steps", "base_tokens_per_sec",
                                  "vs_spec_off", "gen_tokens", "wall_s")}
        except Exception as e:  # noqa: BLE001
            extra["serve_spec_error"] = f"{type(e).__name__}: {e}"[:300]

        # disaggregated serving (docs/SERVING.md "Disaggregated
        # serving"): bursty long-prompt admission against 1 prefill +
        # N decode replicas — decode tok/s (busy-time projection)
        # scaling with N while admitted-TTFT p95 stays flat vs the
        # 1-decode configuration.  Same CPU-plumbing / TPU-numbers
        # split and non-fatality as above.
        try:
            from decode_bench import bench_serve_disagg
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve_disagg(n_decode=2, max_batch=8,
                                           kv_cache_dtype="int8")
                else:
                    r = bench_serve_disagg(preset="tiny", n_decode=2,
                                           max_batch=4, n_requests=10,
                                           prompt_lens=(24, 33, 28, 30),
                                           max_new=24, page_size=8)
            pre = "serve_disagg" if on_tpu else "serve_disagg_cpu"
            extra[f"{pre}_decode_tok_s"] = r["decode_tok_s"]
            extra[f"{pre}_vs_1_decode"] = r["vs_1_decode"]
            extra[f"{pre}_ttft_p95_ms"] = r["ttft_p95_ms"]
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("n_decode", "requests", "kv",
                                  "gen_tokens", "wall_s", "handoffs",
                                  "xfer_bytes",
                                  "ttft_p95_1_decode_ms",
                                  "ttft_p95_colocated_ms",
                                  "decode_tok_s_1_decode",
                                  "colocated_tok_s")}
        except Exception as e:  # noqa: BLE001
            extra["serve_disagg_error"] = f"{type(e).__name__}: {e}"[:300]

        # batched multi-LoRA (docs/SERVING.md "Multi-LoRA"): N adapters
        # + base mixed in one engine's batch (grouped BGMV over the
        # stacked pools) vs the serial one-merged-engine-per-tenant
        # deployment — batched tok/s over the serial busy-time
        # projection.  Same CPU-plumbing / TPU-numbers split and
        # non-fatality as above.
        try:
            from decode_bench import bench_serve_lora
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_serve_lora(n_adapters=3, rank=8,
                                         max_batch=8,
                                         kv_cache_dtype="int8")
                else:
                    r = bench_serve_lora(preset="tiny", n_adapters=3,
                                         rank=8, max_batch=4,
                                         n_requests=8,
                                         prompt_lens=(5, 9, 7, 12),
                                         max_new=8, page_size=8)
            pre = "serve_lora" if on_tpu else "serve_lora_cpu"
            extra[f"{pre}_tok_s"] = r["batched_tok_s"]
            extra[f"{pre}_vs_serial"] = r["vs_serial"]
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("adapters", "rank", "requests", "kv",
                                  "gen_tokens", "wall_s",
                                  "serial_tok_s", "serial_wall_s",
                                  "active_adapters")}
        except Exception as e:  # noqa: BLE001
            extra["serve_lora_error"] = f"{type(e).__name__}: {e}"[:300]

        # decode megakernel (docs/KERNELS.md "Decode megakernel"): bs=1
        # paged decode with the whole decoder layer in ONE dispatch
        # (fused_ops="mega") vs the per-stage fused path.  Rows are
        # backend-tagged (serve_mega vs serve_mega_cpu) so TPU numbers
        # never gate against the CPU baseline; off the chip the Pallas
        # kernel declines and the honest signal is the recorded
        # dispatches-per-step delta, not the tok/s ratio.
        try:
            from decode_bench import bench_decode_mega
            with contextlib.redirect_stdout(sys.stderr):
                if on_tpu:
                    r = bench_decode_mega()
                else:
                    r = bench_decode_mega(preset="tiny", prefill=16,
                                          max_new=24, repeats=2)
            pre = "serve_mega" if on_tpu else "serve_mega_cpu"
            extra[f"{pre}_tok_s"] = r["mega_tok_s"]
            extra[f"{pre}_vs_fused_on"] = r["vs_fused_on"]
            extra[f"{pre}_dispatches_per_step"] = \
                r["mega_dispatches_per_step"]
            extra[f"{pre}_detail"] = {
                k: r[k] for k in ("preset", "prefill", "max_new_tokens",
                                  "on_tok_s", "on_dispatches_per_step")}
        except Exception as e:  # noqa: BLE001
            extra["serve_mega_error"] = f"{type(e).__name__}: {e}"[:300]

        # sharded serving (docs/SERVING.md "Sharded serving"): the
        # TP-partitioned engine and the DP replica router need >= 2
        # devices (a multi-chip slice, or the forced virtual CPU mesh
        # the CI gate / tests run under).  Same CPU-plumbing /
        # TPU-numbers split and non-fatality as the rows above.
        if len(jax.devices()) >= 2:
            try:
                from decode_bench import bench_serve_tp
                with contextlib.redirect_stdout(sys.stderr):
                    if on_tpu:
                        r = bench_serve_tp(tp=2, max_batch=8,
                                           kv_cache_dtype="int8")
                    else:
                        r = bench_serve_tp(preset="tiny", tp=2,
                                           max_batch=2, n_requests=4,
                                           max_new=8,
                                           prompt_lens=(5, 12, 9, 17),
                                           page_size=8, repeats=1)
                extra["serve_tp_tok_s" if on_tpu
                      else "serve_tp_cpu_tok_s"] = r["agg_tokens_per_sec"]
                extra["serve_tp_detail"] = {
                    k: r[k] for k in ("tp", "max_batch", "requests", "kv",
                                      "gen_tokens", "wall_s")}
            except Exception as e:  # noqa: BLE001
                extra["serve_tp_error"] = f"{type(e).__name__}: {e}"[:300]

            try:
                from decode_bench import bench_serve_dp
                with contextlib.redirect_stdout(sys.stderr):
                    if on_tpu:
                        r = bench_serve_dp(replicas=2, max_batch=8,
                                           kv_cache_dtype="int8")
                    else:
                        r = bench_serve_dp(preset="tiny", replicas=2,
                                           max_batch=4, n_requests=16,
                                           prompt_lens=(24,), max_new=32,
                                           page_size=8)
                pre = "serve_dp" if on_tpu else "serve_dp_cpu"
                extra[f"{pre}_agg_tok_s"] = r["agg_tokens_per_sec"]
                extra[f"{pre}_vs_single_replica"] = r["vs_single_replica"]
                extra[f"{pre}_detail"] = {
                    k: r[k] for k in ("replicas", "tp", "max_batch",
                                      "requests", "gen_tokens", "wall_s",
                                      "wall_tokens_per_sec",
                                      "single_replica_tok_s")}
            except Exception as e:  # noqa: BLE001
                extra["serve_dp_error"] = f"{type(e).__name__}: {e}"[:300]

    result = {
        "metric": "llama_train_mfu",
        "value": round(mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": extra,
    }
    if tel is not None:
        # the sidecar carries the same payload the driver records, plus
        # the final registry snapshot (via disable's flush)
        tel.emit({"event": "bench_result", **result})
        from paddle_tpu import observability as obs
        obs.disable()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
