"""Autograd façade (``paddle.autograd`` / ``paddle.grad`` parity).

The reference implements a C++ eager tape (paddle/fluid/eager/backward.cc,
``egr::Backward``); on TPU the whole training step is traced and
differentiated by ``jax.grad``, which removes the per-op dispatch boundary
entirely (SURVEY.md §3.1).  This module provides:

- ``grad`` / ``value_and_grad`` over a Layer's parameters via the
  functional bridge;
- ``PyLayer`` parity via ``jax.custom_vjp``;
- ``no_grad`` (trivially a no-op marker since grads are explicit).
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict, Optional

import jax

from ..nn.layer import Layer, functional_call, raw_params, trainable_mask


def value_and_grad(layer: Layer, loss_fn: Callable, has_aux: bool = False):
    """Build ``fn(params, *args, rngs=None) -> ((loss, aux?), grads)``.

    ``loss_fn(outputs, *args) -> scalar`` consumes the layer outputs, or pass
    ``loss_fn=None`` and make the layer itself return the scalar loss.
    Non-trainable parameters receive zero gradients (masked like the
    reference's ``stop_gradient``).
    """
    mask = trainable_mask(layer)

    def pure_loss(train_params, frozen_params, args, kwargs, rngs):
        params = {**frozen_params, **train_params}
        out = functional_call(layer, params, *args, rngs=rngs, training=True,
                              **kwargs)
        return out if loss_fn is None else loss_fn(out, *args)

    vag = jax.value_and_grad(pure_loss, has_aux=has_aux)

    def fn(params: Dict[str, jax.Array], *args, rngs=None, **kwargs):
        train = {k: v for k, v in params.items() if mask.get(k, True)}
        frozen = {k: v for k, v in params.items() if not mask.get(k, True)}
        val, grads = vag(train, frozen, args, kwargs, rngs)
        return val, grads

    return fn


def grad(layer: Layer, loss_fn: Callable = None, has_aux: bool = False):
    vag = value_and_grad(layer, loss_fn, has_aux=has_aux)

    def fn(params, *args, **kwargs):
        _, g = vag(params, *args, **kwargs)
        return g

    return fn


_grad_enabled = [True]


@contextlib.contextmanager
def no_grad():
    """API parity: jax only differentiates what you ask it to, so this is a
    documentation-level marker (kept so reference code ports cleanly); it
    still flips the queryable flag for code that branches on it."""
    prev = _grad_enabled[0]
    _grad_enabled[0] = False
    try:
        yield
    finally:
        _grad_enabled[0] = prev


def is_grad_enabled() -> bool:
    """Reference: paddle.is_grad_enabled — the eager-mode flag no_grad/
    set_grad_enabled toggle (grads themselves are always explicit here)."""
    return _grad_enabled[0]


class _GradMode:
    """Flips the flag IMMEDIATELY (imperative torch/paddle style) and also
    works as a context manager that restores the previous mode on exit."""

    def __init__(self, mode: bool):
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False


def set_grad_enabled(mode: bool):
    """Reference: paddle.set_grad_enabled — a plain call takes effect
    immediately; `with` additionally restores the previous mode."""
    return _GradMode(mode)


def enable_grad():
    """Reference: paddle.enable_grad — re-enable grad inside a no_grad
    region (context manager, same flag no_grad toggles)."""
    return _GradMode(True)


# saved_tensors_hooks (reference: paddle.autograd.saved_tensors_hooks —
# python/paddle/autograd/saved_tensors_hooks.py).  The hooks wrap what
# PyLayer.ctx.save_for_backward stores: pack_hook runs at save time,
# unpack_hook when the backward reads it — the same contract the reference
# uses for CPU-offload / recompute of residuals.
_saved_tensor_hooks = []


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook, self.unpack_hook = pack_hook, unpack_hook

    def __enter__(self):
        _saved_tensor_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensor_hooks.pop()
        return False


class PyLayer:
    """``paddle.autograd.PyLayer`` parity on ``jax.custom_vjp``.

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx, *grads)``;
    call via ``MyLayer.apply(*args)``.  ``ctx.save_for_backward(*ts)`` stores
    residuals.
    """

    class _Ctx:
        def __init__(self):
            self.saved = ()
            # hook pair captured at SAVE time (reference semantics: the
            # unpack hook applies at backward even after the `with` exits)
            self._hooks = _saved_tensor_hooks[-1] if _saved_tensor_hooks \
                else None

        def save_for_backward(self, *tensors):
            if self._hooks is not None:
                tensors = tuple(self._hooks[0](t) for t in tensors)
            self.saved = tensors

        def saved_tensor(self):
            if self._hooks is not None:
                return tuple(self._hooks[1](t) for t in self.saved)
            return self.saved

    @classmethod
    def apply(cls, *args):
        @jax.custom_vjp
        def f(*xs):
            ctx = cls._Ctx()
            return cls.forward(ctx, *xs)

        def fwd(*xs):
            ctx = cls._Ctx()
            out = cls.forward(ctx, *xs)
            return out, ctx.saved

        # hook pair active at apply() time rides the closure so backward
        # unpacks with it even after the `with saved_tensors_hooks` exits
        hooks = _saved_tensor_hooks[-1] if _saved_tensor_hooks else None

        def bwd(saved, g):
            ctx = cls._Ctx()
            ctx._hooks = hooks
            ctx.saved = saved
            grads = cls.backward(ctx, g)
            return grads if isinstance(grads, tuple) else (grads,)

        f.defvjp(fwd, bwd)
        return f(*args)


# reference: paddle.autograd.PyLayerContext — the ctx object forward/
# backward receive; exposed so `isinstance(ctx, PyLayerContext)` works
PyLayerContext = PyLayer._Ctx


def backward(tensors, grad_tensors=None):  # pragma: no cover - guidance only
    raise RuntimeError(
        "paddle_tpu has no eager tape: use paddle_tpu.autograd.value_and_grad "
        "or the Trainer/jit.train_step compiled path (see docs/MIGRATION.md). "
        "Reference parity: egr::Backward is replaced by jax.grad tracing.")


# ---------------------------------------------------------------------------
# functional higher-order AD (reference: paddle.autograd.jacobian/hessian,
# paddle.incubate.autograd.{Jacobian,Hessian,jvp,vjp} — python/paddle/
# autograd/autograd.py). On TPU these ARE jax's transforms; the wrappers
# keep the reference call shapes.
# ---------------------------------------------------------------------------

def jacobian(func, xs, batch_axis=None, mode="rev"):
    """J[i,j] = d func(xs)[i] / d xs[j]. ``mode``: 'rev' (jacrev, tall
    Jacobians) or 'fwd' (jacfwd, wide Jacobians)."""
    import jax
    jac_fn = jax.jacrev if mode == "rev" else jax.jacfwd
    if batch_axis is None:
        return jac_fn(func)(xs)
    if batch_axis != 0:
        raise ValueError("batch_axis must be None or 0")
    return jax.vmap(jac_fn(func))(xs)


def hessian(func, xs, batch_axis=None):
    """H[i,j] = d^2 func(xs) / d xs[i] d xs[j] for scalar-output func."""
    import jax
    if batch_axis is None:
        return jax.hessian(func)(xs)
    if batch_axis != 0:
        raise ValueError("batch_axis must be None or 0")
    return jax.vmap(jax.hessian(func))(xs)


def jvp(func, xs, v):
    """Forward-mode: (func(xs), J @ v) — reference incubate.autograd.jvp."""
    import jax
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    v = v if isinstance(v, (tuple, list)) else (v,)
    return jax.jvp(func, tuple(xs), tuple(v))


def vjp(func, xs, v=None):
    """Reverse-mode: (func(xs), v^T @ J) — reference incubate.autograd.vjp.
    With v=None and scalar output, returns plain gradients."""
    import jax
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    out, pullback = jax.vjp(func, *xs)
    if v is None:
        import jax.numpy as jnp
        v = jnp.ones_like(out)
    grads = pullback(v)
    return out, grads if len(grads) > 1 else grads[0]


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
