"""paddle_tpu.linalg — importable module form of the linalg namespace.

Reference: python/paddle/linalg.py (a re-export module over
tensor/linalg.py).  The op implementations live on ``ops.linalg``; this
module hoists them so both ``paddle_tpu.linalg.svd`` and
``import paddle_tpu.linalg`` work, exactly like the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops import linalg as _ns


def lu_solve(b, lu, pivots, trans="N", name=None):
    """Reference: paddle.linalg.lu_solve — solve A x = b given the packed
    LU factorization (1-based sequential pivots, paddle.linalg.lu's
    convention)."""
    piv0 = jnp.asarray(pivots, jnp.int32) - 1
    t = {"N": 0, "T": 1, "C": 2}[trans] if isinstance(trans, str) else trans
    return jax.scipy.linalg.lu_solve((jnp.asarray(lu), piv0),
                                     jnp.asarray(b), trans=t)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference: paddle.linalg.pca_lowrank — randomized PCA returning
    (U, S, V) with x ≈ U diag(S) V^T after centering."""
    x = jnp.asarray(x)
    m, n = x.shape[-2], x.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    u, s, v = _ns.svd_lowrank(x, q=q, niter=niter)  # v is already V, not V^H
    return u, s, v


_EXPORTED = [n for n in dir(_ns) if not n.startswith("_")]
for _n in _EXPORTED:
    globals()[_n] = getattr(_ns, _n)
del _n

__all__ = sorted(set(_EXPORTED) | {"lu_solve", "pca_lowrank"})
