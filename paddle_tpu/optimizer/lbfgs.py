"""L-BFGS with strong-Wolfe line search (reference:
python/paddle/optimizer/lbfgs.py).

The reference's ``step(closure)`` re-runs the closure which calls
``loss.backward()`` into parameter ``.grad`` slots — an eager-tape
contract that does not exist here.  The jax-idiomatic contract (documented
deviation): the closure is a PURE function of the parameter pytree,
``closure(params) -> loss``; value+grad at line-search trial points come
from ``jax.value_and_grad`` of that function, jitted once.  Everything
else (two-loop recursion, history rules, strong-Wolfe/backtracking line
search, tolerances) follows the reference/torch algorithm.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["LBFGS"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    # torch/reference cubic interpolation for strong Wolfe
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    sq = d1 * d1 - g1 * g2
    if sq >= 0:
        d2 = sq ** 0.5
        denom = (g2 - g1 + 2 * d2) if x1 <= x2 else (g1 - g2 + 2 * d2)
        if denom == 0.0:   # plateau bracket: fall back to bisection
            return (lo + hi) / 2.0
        if x1 <= x2:
            pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / denom)
        else:
            pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / denom)
        return min(max(pos, lo), hi)
    return (lo + hi) / 2.0


class LBFGS:
    """Reference surface: ``LBFGS(learning_rate, max_iter, ...,
    parameters=model.parameters())`` + ``opt.step(closure)``.

    ``closure(params) -> loss`` must be pure (params pytree in, scalar
    out); ``step`` runs up to ``max_iter`` L-BFGS iterations and writes
    the result back into the owning model (when constructed from
    ``model.parameters()``) or returns it."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-07, tolerance_change=1e-09,
                 history_size=100, line_search_fn: Optional[str] = None,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 name=None):
        from ..nn.layer import ParameterList
        del name  # reference signature compat
        self.lr = float(learning_rate)
        from ..regularizer import L2Decay
        self.weight_decay = (weight_decay.coeff
                             if isinstance(weight_decay, L2Decay)
                             else float(weight_decay or 0.0))
        if grad_clip is not None:
            # clipping inside a Wolfe line search breaks its descent
            # assumptions; the reference accepts-and-applies, we reject
            # loudly rather than silently diverge
            raise NotImplementedError(
                "grad_clip with LBFGS is not supported (the line search "
                "owns the step length); clip inside the closure if needed")
        self.max_iter = int(max_iter)
        self.max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"line_search_fn must be None or 'strong_wolfe', got "
                f"{line_search_fn!r}")
        self.line_search_fn = line_search_fn
        self._owner = None
        self._names = None
        if isinstance(parameters, ParameterList):
            self._owner = parameters.owner
            self._names = parameters.names
        self._vg = None          # jitted value_and_grad of the closure
        self._closure_id = None

    # -- internals ---------------------------------------------------------

    def _value_and_grad(self, closure):
        if self._vg is None or self._closure_id != id(closure):
            wd = self.weight_decay

            def objective(flat, unravel):
                loss = closure(unravel(flat))
                if wd:
                    # L2 regularization folded into the objective so the
                    # line search sees the same function it differentiates
                    loss = loss + 0.5 * wd * jnp.sum(flat * flat)
                return loss

            self._vg = jax.jit(jax.value_and_grad(objective, argnums=0),
                               static_argnums=(1,))
            self._closure_id = id(closure)
        return self._vg

    def _strong_wolfe(self, vg, unravel, flat, direction, f0, g0_dot, t,
                      c1=1e-4, c2=0.9, max_ls=25):
        """Bracket + zoom strong-Wolfe search along ``direction``.

        Returns (t, f, g_vec, evals) — the gradient VECTOR at the accepted
        point rides along so the caller never re-evaluates it."""
        def phi(step):
            f, g = vg(flat + step * direction, unravel)
            return float(f), g

        f_prev, g_prev_dot, t_prev = f0, g0_dot, 0.0
        g_prev_vec = None
        f_new, g_new = phi(t)
        g_new_dot = float(g_new @ direction)
        evals = 1
        bracket = None
        for _ in range(max_ls):
            if f_new > f0 + c1 * t * g0_dot or \
                    (evals > 1 and f_new >= f_prev):
                bracket = ((t_prev, f_prev, g_prev_dot, g_prev_vec),
                           (t, f_new, g_new_dot, g_new))
                break
            if abs(g_new_dot) <= -c2 * g0_dot:
                return t, f_new, g_new, evals     # Wolfe satisfied
            if g_new_dot >= 0:
                bracket = ((t, f_new, g_new_dot, g_new),
                           (t_prev, f_prev, g_prev_dot, g_prev_vec))
                break
            t_prev, f_prev, g_prev_dot = t, f_new, g_new_dot
            g_prev_vec = g_new
            t = min(10 * t, 1e10)
            f_new, g_new = phi(t)
            g_new_dot = float(g_new @ direction)
            evals += 1
        if bracket is None:
            return t, f_new, g_new, evals
        (lo_t, lo_f, lo_g, lo_vec), (hi_t, hi_f, hi_g, _) = bracket
        for _ in range(max_ls):
            if abs(hi_t - lo_t) < 1e-9:
                break
            t = _cubic_interpolate(lo_t, lo_f, lo_g, hi_t, hi_f, hi_g)
            f_new, g_new = phi(t)
            g_new_dot = float(g_new @ direction)
            evals += 1
            if f_new > f0 + c1 * t * g0_dot or f_new >= lo_f:
                hi_t, hi_f, hi_g = t, f_new, g_new_dot
            else:
                if abs(g_new_dot) <= -c2 * g0_dot:
                    return t, f_new, g_new, evals
                if g_new_dot * (hi_t - lo_t) >= 0:
                    hi_t, hi_f, hi_g = lo_t, lo_f, lo_g
                lo_t, lo_f, lo_g = t, f_new, g_new_dot
                lo_vec = g_new
        if lo_vec is None:   # zoom never accepted a point past t=0
            _, lo_vec = phi(lo_t)
            evals += 1
        return lo_t, lo_f, lo_vec, evals

    # -- reference surface -------------------------------------------------

    def step(self, closure: Callable):
        from ..nn.layer import raw_params

        if self._owner is not None:
            params = {k: v for k, v in raw_params(self._owner).items()
                      if self._names is None or k in self._names}
        else:
            raise RuntimeError(
                "pass parameters=model.parameters() so step() knows what "
                "to optimize, or use minimize(closure, params)")
        new_params, loss = self.minimize(closure, params)
        for k, v in new_params.items():
            self._owner._assign_by_path(k, v)
        return loss

    def minimize(self, closure: Callable, params):
        """Functional form: → (optimized params, final loss)."""
        from jax.flatten_util import ravel_pytree
        flat, unravel = ravel_pytree(params)
        vg = self._value_and_grad(closure)
        f, g = vg(flat, unravel)
        f = float(f)
        evals = 1
        s_hist, y_hist, rho_hist = [], [], []
        for it in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tol_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y, rho in zip(reversed(s_hist), reversed(y_hist),
                                 reversed(rho_hist)):
                a = rho * float(s @ q)
                alphas.append(a)
                q = q - a * y
            if y_hist:
                gamma = float(s_hist[-1] @ y_hist[-1]) / max(
                    float(y_hist[-1] @ y_hist[-1]), 1e-20)
                q = q * gamma
            for (s, y, rho), a in zip(zip(s_hist, y_hist, rho_hist),
                                      reversed(alphas)):
                b = rho * float(y @ q)
                q = q + (a - b) * s
            direction = -q
            g_dot = float(g @ direction)
            if g_dot > -1e-20:   # not a descent direction: reset history
                direction = -g
                g_dot = float(g @ direction)
                s_hist, y_hist, rho_hist = [], [], []
            t = self.lr if it > 0 else min(
                1.0, 1.0 / max(float(jnp.sum(jnp.abs(g))), 1e-20)) * self.lr
            if self.line_search_fn == "strong_wolfe":
                # the search returns f and the grad VECTOR at the accepted
                # point — no re-evaluation needed
                t, f_new, g_new, used = self._strong_wolfe(
                    vg, unravel, flat, direction, f, g_dot, t)
                new_flat = flat + t * direction
                evals += used
            else:
                new_flat = flat + t * direction
                f2, g_new = vg(new_flat, unravel)
                f_new = float(f2)
                evals += 1
            s = new_flat - flat
            y = g_new - g
            sy = float(s @ y)
            if sy > 1e-10:
                if len(s_hist) >= self.history_size:
                    s_hist.pop(0)
                    y_hist.pop(0)
                    rho_hist.pop(0)
                s_hist.append(s)
                y_hist.append(y)
                rho_hist.append(1.0 / sy)
            converged = (abs(f_new - f) < self.tol_change
                         or float(jnp.max(jnp.abs(s))) < self.tol_change)
            flat, f, g = new_flat, f_new, g_new
            if converged or evals >= self.max_eval:
                break
        return unravel(flat), f
