"""Optimizers (``paddle.optimizer`` parity), as pure pytree transforms.

Reference: python/paddle/optimizer/{optimizer,adamw,momentum,lamb}.py and the
fused CUDA kernels paddle/phi/kernels/gpu/{adamw,fused_adam,lamb}_kernel.cu.
On TPU a "fused multi-tensor optimizer kernel" is simply the XLA-fused update
over the whole parameter pytree inside the compiled step — no hand fusion
needed.  Design:

- ``opt.init(params) -> state`` and ``opt.apply(grads, state, params) ->
  (new_params, new_state)`` are the pure core (used by jit.TrainStep).
- ``multi_precision`` master weights (fp32 copies of low-precision params)
  follow the reference's MPType pattern: update in fp32, cast back to the
  param dtype, keep the fp32 master in optimizer state.
- The paddle-style stateful surface (``opt.step()``/``clear_grad``) works
  eagerly for small-model/debug use via the owning Layer captured from
  ``parameters=model.parameters()``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp

from ..nn.clip import ClipGradBase, ClipGradByGlobalNorm
from ..nn.layer import Layer, ParameterList, raw_params
from . import lr as lr_mod
from .lr import LRScheduler

PyTree = Any


def _lr_value(lr, step):
    if isinstance(lr, LRScheduler):
        return lr.lr_at(step)
    return jnp.asarray(lr, jnp.float32)


class Optimizer:
    """Base optimizer: pure functional core + paddle-style surface."""

    def __init__(self, learning_rate=0.001, parameters: Optional[ParameterList] = None,
                 weight_decay=0.0, grad_clip: Optional[ClipGradBase] = None,
                 multi_precision=False, apply_decay_param_fun: Optional[Callable] = None):
        self._lr = learning_rate
        self.weight_decay = weight_decay or 0.0
        # paddle.regularizer objects are accepted wherever a scalar is
        # (reference: optimizer.py regularization= / weight_decay=)
        from ..regularizer import L1Decay, L2Decay
        self._l1_coeff = 0.0
        if isinstance(self.weight_decay, L1Decay):
            self._l1_coeff = self.weight_decay.coeff
            self._wd_coeff = 0.0
        elif isinstance(self.weight_decay, L2Decay):
            self._wd_coeff = self.weight_decay.coeff
        else:
            self._wd_coeff = float(self.weight_decay)
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        self.master_grad = False  # set by amp.decorate(master_grad=True)
        self.apply_decay_param_fun = apply_decay_param_fun
        self._owner: Optional[Layer] = None
        self._names = None
        if isinstance(parameters, ParameterList):
            self._owner = parameters.owner
            self._names = parameters.names
        self._eager_state = None

    # ---- functional core --------------------------------------------------

    def init(self, params: PyTree) -> PyTree:
        state = {"step": jnp.zeros((), jnp.int32)}
        if self.multi_precision:
            state["master"] = jax.tree.map(
                lambda p: p.astype(jnp.float32)
                if jnp.issubdtype(p.dtype, jnp.floating) and p.dtype != jnp.float32
                else None, params)
        state.update(self._init_slots(params))
        return state

    def _init_slots(self, params: PyTree) -> Dict[str, PyTree]:
        return {}

    def _update_one(self, name, p, g, lr, state_slots, step):
        raise NotImplementedError

    def _decay_mask(self, params: Dict[str, jax.Array]) -> Dict[str, bool]:
        if self.apply_decay_param_fun is None:
            return {k: True for k in params}
        return {k: bool(self.apply_decay_param_fun(k)) for k in params}

    def _update_rows(self, name, p, rg, lr, slots, step, wd):
        """Rows-sparse update (grad is a RowsGrad).  Default: densify and
        run the dense rule; SGD/Adam override with true sparse updates
        (reference: phi selected_rows kernels)."""
        return self._update_one(name, p, rg.to_dense().astype(jnp.float32),
                                lr, slots, step, wd)

    def apply(self, grads: Dict[str, jax.Array], state: PyTree,
              params: Dict[str, jax.Array]):
        """Pure update. grads may cover a subset of params (frozen ones
        skipped).  A grad leaf may be a ``sparse.RowsGrad`` — it bypasses
        grad_clip/master_grad promotion (reference: SelectedRows grads are
        exempt from global-norm clip in the dense path) and routes to the
        optimizer's sparse rule."""
        from ..sparse.rows import RowsGrad
        rows_grads = {k: g for k, g in grads.items()
                      if isinstance(g, RowsGrad)}
        grads = {k: g for k, g in grads.items()
                 if not isinstance(g, RowsGrad)}
        if getattr(self, "master_grad", False):
            # amp master_grad: promote low-precision grads before clipping
            # so the global-norm (and every later consumer) sees fp32
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32)
                if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step = state["step"]
        lr = _lr_value(self._lr, step)
        masters = state.get("master", {})
        new_params, new_state = dict(params), {k: dict(v) if isinstance(v, dict) else v
                                               for k, v in state.items()}
        decay_mask = self._decay_mask(params)
        for name, g in grads.items():
            p = params[name]
            master = masters.get(name) if isinstance(masters, dict) else None
            p_compute = master if master is not None else p
            slots = {k: v[name] for k, v in state.items()
                     if isinstance(v, dict) and k not in ("master",) and name in v}
            wd = self._wd_coeff if decay_mask.get(name, True) else 0.0
            if self._l1_coeff and decay_mask.get(name, True):
                # L1Decay: subgradient of coeff*|w| added to the grad
                g = g + self._l1_coeff * jnp.sign(p_compute)
            new_p, new_slots = self._update_one(
                name, p_compute.astype(jnp.float32), g.astype(jnp.float32),
                lr, slots, step, wd)
            if master is not None:
                new_state["master"][name] = new_p
                new_params[name] = new_p.astype(p.dtype)
            else:
                new_params[name] = new_p.astype(p.dtype)
            for k, v in new_slots.items():
                new_state[k][name] = v
        for name, rg in rows_grads.items():
            p = params[name]
            master = masters.get(name) if isinstance(masters, dict) else None
            p_compute = master if master is not None else p
            slots = {k: v[name] for k, v in state.items()
                     if isinstance(v, dict) and k not in ("master",) and name in v}
            wd = self._wd_coeff if decay_mask.get(name, True) else 0.0
            new_p, new_slots = self._update_rows(
                name, p_compute.astype(jnp.float32), rg, lr, slots, step, wd)
            if master is not None:
                new_state["master"][name] = new_p
            new_params[name] = new_p.astype(p.dtype)
            for k, v in new_slots.items():
                new_state[k][name] = v
        new_state["step"] = step + 1
        return new_params, new_state

    # ---- paddle-style eager surface --------------------------------------

    def step(self):
        if self._owner is None:
            raise RuntimeError("pass parameters=model.parameters() to use .step()")
        if not hasattr(self, "_eager_grads") or self._eager_grads is None:
            raise RuntimeError(
                "no gradients staged: call opt.set_grads(grads) first, or use "
                "the compiled paddle_tpu.jit.TrainStep path")
        params = raw_params(self._owner)
        if self._eager_state is None:
            self._eager_state = self.init(params)
        new_params, self._eager_state = self.apply(self._eager_grads, self._eager_state, params)
        for k, v in new_params.items():
            self._owner._assign_by_path(k, v)
        self._eager_grads = None

    def set_grads(self, grads: Dict[str, jax.Array]):
        self._eager_grads = grads

    def clear_grad(self):
        self._eager_grads = None

    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr.get_lr()
        return float(self._lr)

    def set_lr(self, value):
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    def state_dict(self):
        return self._eager_state or {}

    def set_state_dict(self, d):
        self._eager_state = d


class SGD(Optimizer):
    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, {}

    def _update_rows(self, name, p, rg, lr, slots, step, wd):
        """Scatter-add update: on touched rows this exactly equals the
        dense rule (SGD is linear in the grad, so duplicate rows need no
        coalescing); weight decay applies to touched rows only (reference
        sparse-SGD semantics), using pre-update values like the dense
        ``g + wd*p``."""
        if wd:
            cg = rg.coalesce()
            touched = p.at[cg.rows].get(mode="fill", fill_value=0.0)
            p = p.at[cg.rows].add(-lr * wd * touched, mode="drop")
        return p.at[rg.rows].add(-lr * rg.values.astype(p.dtype),
                                 mode="drop"), {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=0.0, grad_clip=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, params):
        return {"velocity": jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        v = self.momentum * slots["velocity"] + g
        if self.use_nesterov:
            p = p - lr * (g + self.momentum * v)
        else:
            p = p - lr * v
        return p, {"velocity": v}


class LarsMomentum(Momentum):
    """Reference: paddle.optimizer.LarsMomentum — layer-adaptive rate
    scaling: local_lr = lr * lars_coeff * ||w|| / (||g|| + wd*||w||)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, grad_clip=None,
                 multi_precision=False, epsilon=1e-9):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=0.0, grad_clip=grad_clip,
                         multi_precision=multi_precision)
        self.lars_coeff = lars_coeff
        self.lars_wd = lars_weight_decay
        self.epsilon = epsilon

    def _update_one(self, name, p, g, lr, slots, step, wd):
        w_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self.lars_coeff * w_norm
            / (g_norm + self.lars_wd * w_norm + self.epsilon), lr)
        g = g + self.lars_wd * p
        v = self.momentum * slots["velocity"] + local * g
        return p - v, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 multi_precision=False, lazy_mode=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_mode = lazy_mode

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"moment1": jax.tree.map(z, params),
                "moment2": jax.tree.map(z, params)}

    def _update_rows(self, name, p, rg, lr, slots, step, wd):
        """``lazy_mode`` sparse Adam (reference:
        AdamDenseParamSparseGradKernel): moments and parameter update only
        for the touched (unique) rows; untouched rows keep stale moments.
        Without lazy_mode the RowsGrad densifies and every row's moments
        decay, exactly like dense Adam on a mostly-zero grad."""
        if not self.lazy_mode:
            return super()._update_rows(name, p, rg, lr, slots, step, wd)
        cg = rg.coalesce()
        rows = cg.rows
        g = cg.values.astype(jnp.float32)
        m, v = slots["moment1"], slots["moment2"]
        p_r = p.at[rows].get(mode="fill", fill_value=0.0)
        m_r = m.at[rows].get(mode="fill", fill_value=0.0)
        v_r = v.at[rows].get(mode="fill", fill_value=0.0)
        new_p_r, m_r, v_r = self._adam_core(p_r, g, lr, m_r, v_r, step, wd,
                                            decoupled=False)
        return (p.at[rows].set(new_p_r, mode="drop"),
                {"moment1": m.at[rows].set(m_r, mode="drop"),
                 "moment2": v.at[rows].set(v_r, mode="drop")})

    def _adam_core(self, p, g, lr, m, v, step, wd, decoupled):
        if wd and not decoupled:
            g = g + wd * p
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        t = (step + 1).astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        update = mhat / (jnp.sqrt(vhat) + self.epsilon)
        if wd and decoupled:
            update = update + wd * p
        return p - lr * update, m, v

    def _update_one(self, name, p, g, lr, slots, step, wd):
        new_p, m, v = self._adam_core(p, g, lr, slots["moment1"], slots["moment2"],
                                      step, wd, decoupled=False)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: AdamwDenseKernel).

    ``use_fused``: route eligible parameter updates through the fused
    Pallas AdamW kernel (ops/pallas/fused_adamw.py) — moments + param in
    one elementwise pass over aliased buffers on TPU.  ``None`` (auto)
    uses the kernel wherever its dispatch serves (TPU backend, f32
    lane-aligned params); ``False`` pins the XLA composition.  Both
    compute the same formula (tests/test_fused_kernels.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, grad_clip=None,
                 multi_precision=False, apply_decay_param_fun=None, lr_ratio=None,
                 use_fused=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision)
        self.apply_decay_param_fun = apply_decay_param_fun
        self.use_fused = use_fused

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if self.use_fused is not False:
            from ..ops import dispatch
            impl = dispatch.get("fused_adamw")
            if impl is not None:
                t = (step + 1).astype(jnp.float32)
                out = impl(p, g, slots["moment1"], slots["moment2"],
                           jnp.asarray(lr, jnp.float32),
                           1.0 / (1.0 - self.beta1 ** t),
                           1.0 / (1.0 - self.beta2 ** t),
                           beta1=self.beta1, beta2=self.beta2,
                           eps=self.epsilon, wd=float(wd))
                if out is not None:
                    new_p, m, v = out
                    return new_p, {"moment1": m, "moment2": v}
        new_p, m, v = self._adam_core(p, g, lr, slots["moment1"], slots["moment2"],
                                      step, wd, decoupled=True)
        return new_p, {"moment1": m, "moment2": v}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"moment1": jax.tree.map(z, params),
                "moment2": jax.tree.map(z, params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if self.exclude_fn is not None and self.exclude_fn(name):
            wd = 0.0
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g)
        t = (step + 1).astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=0.0, grad_clip=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _init_slots(self, params):
        return {"moment": jax.tree.map(
            lambda p: jnp.full(p.shape, self.init_acc, jnp.float32), params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        acc = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self.epsilon), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=0.0, grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        slots = {"mean_square": jax.tree.map(z, params),
                 "momentum_acc": jax.tree.map(z, params)}
        if self.centered:
            slots["mean_grad"] = jax.tree.map(z, params)
        return slots

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(g)
        out_slots = {"mean_square": ms}
        denom = ms
        if self.centered:
            mg = self.rho * slots["mean_grad"] + (1 - self.rho) * g
            denom = ms - jnp.square(mg)
            out_slots["mean_grad"] = mg
        mom = self.momentum * slots["momentum_acc"] + lr * g / jnp.sqrt(denom + self.epsilon)
        out_slots["momentum_acc"] = mom
        return p - mom, out_slots


__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Lamb", "Adagrad",
           "RMSProp", "lr", "LRScheduler"]

lr = lr_mod


class Adadelta(Optimizer):
    """Reference: paddle.optimizer.Adadelta (adadelta kernel)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=0.0, grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon, self.rho = epsilon, rho

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"avg_squared_grad": jax.tree.map(z, params),
                "avg_squared_update": jax.tree.map(z, params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        asg = self.rho * slots["avg_squared_grad"] + (1 - self.rho) * jnp.square(g)
        asu = slots["avg_squared_update"]
        update = g * jnp.sqrt(asu + self.epsilon) / jnp.sqrt(asg + self.epsilon)
        asu = self.rho * asu + (1 - self.rho) * jnp.square(update)
        return p - lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adamax(Optimizer):
    """Reference: paddle.optimizer.Adamax (infinity-norm Adam variant)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"moment": jax.tree.map(z, params),
                "inf_norm": jax.tree.map(z, params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        m = self.beta1 * slots["moment"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["inf_norm"], jnp.abs(g))
        t = step + 1
        lr_t = lr / (1 - self.beta1 ** t)
        return p - lr_t * m / (u + self.epsilon), {"moment": m, "inf_norm": u}


__all__ += ["Adadelta", "Adamax"]


class ASGD(Optimizer):
    """Averaged SGD (reference: paddle.optimizer.ASGD) — plain SGD steps
    plus a running average of the iterates; ``averaged_params`` of the
    state is what evaluation should use."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.batch_num = batch_num

    def _init_slots(self, params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {"avg": jax.tree.map(z, params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        new_p = p - lr * g
        t = (step + 1).astype(jnp.float32)
        avg = slots["avg"] + (new_p - slots["avg"]) / t
        return new_p, {"avg": avg}


class Rprop(Optimizer):
    """Resilient backprop (reference: paddle.optimizer.Rprop) — per-weight
    step sizes grown/shrunk by the sign agreement of successive grads;
    full-batch regimes only (the reference documents the same)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, 0.0, grad_clip,
                         multi_precision)
        self.lr_min, self.lr_max = learning_rate_range
        self.eta_minus, self.eta_plus = etas

    def _init_slots(self, params):
        # schedulers work too: seed the per-weight step sizes from the
        # step-0 learning rate
        lr0 = float(_lr_value(self._lr, jnp.zeros((), jnp.int32)))
        return {"prev_grad": jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "step_size": jax.tree.map(
                lambda p: jnp.full(p.shape, lr0, jnp.float32), params)}

    def _update_one(self, name, p, g, lr, slots, step, wd):
        sign = jnp.sign(g * slots["prev_grad"])
        size = jnp.clip(
            jnp.where(sign > 0, slots["step_size"] * self.eta_plus,
                      jnp.where(sign < 0, slots["step_size"] * self.eta_minus,
                                slots["step_size"])),
            self.lr_min, self.lr_max)
        # sign flip: no step this iteration (classic Rprop-), grad zeroed
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * size
        return new_p, {"prev_grad": g_eff, "step_size": size}


class NAdam(Adam):
    """Adam with Nesterov momentum (reference: paddle.optimizer.NAdam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision)
        self.momentum_decay = momentum_decay

    def _init_slots(self, params):
        slots = super()._init_slots(params)
        slots["mu_product"] = jax.tree.map(
            lambda p: jnp.ones((), jnp.float32), params)
        return slots

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        t = (step + 1).astype(jnp.float32)
        mu_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.momentum_decay))
        mu_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) *
                                                 self.momentum_decay))
        mu_prod = slots["mu_product"] * mu_t
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * g * g
        m_hat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                 + (1 - mu_t) * g / (1 - mu_prod))
        v_hat = v / (1 - self.beta2 ** t)
        new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + self.epsilon)
        return new_p, {"moment1": m, "moment2": v, "mu_product": mu_prod}


class RAdam(Adam):
    """Rectified Adam (reference: paddle.optimizer.RAdam) — per-step
    variance rectification; falls back to un-adapted momentum while the
    variance estimate is unreliable."""

    def _update_one(self, name, p, g, lr, slots, step, wd):
        if wd:
            g = g + wd * p
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * g * g
        m_hat = m / (1 - self.beta1 ** t)
        rho_inf = 2.0 / (1 - self.beta2) - 1.0
        rho_t = rho_inf - 2.0 * t * self.beta2 ** t / (1 - self.beta2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                     / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                   1e-12))
        v_hat = jnp.sqrt(v / (1 - self.beta2 ** t))
        adaptive = p - lr * r * m_hat / (v_hat + self.epsilon)
        plain = p - lr * m_hat
        new_p = jnp.where(rho_t > 5.0, adaptive, plain)
        return new_p, {"moment1": m, "moment2": v}


__all__ += ["ASGD", "Rprop", "NAdam", "RAdam"]


from .lbfgs import LBFGS  # noqa: E402,F401

__all__ += ["LBFGS"]
