"""LR schedulers (``paddle.optimizer.lr`` parity).

Reference: python/paddle/optimizer/lr.py.  Each scheduler is a pure function
of the integer step so it can live inside a compiled train step (the
reference mutates host-side state and re-feeds the LR each step; here the LR
is computed on-device from the step counter — no host sync).  The stateful
``.step()/.get_lr()`` API is kept for parity.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.step()  # advance to epoch 0, paddle semantics

    # pure form: override this
    def lr_at(self, step):
        return jnp.asarray(self.base_lr, jnp.float32)

    # stateful parity API
    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1

    def get_lr(self):
        return float(self.lr_at(jnp.asarray(self.last_epoch)))

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, d):
        self.last_epoch = d["last_epoch"]

    def __call__(self, step):
        return self.lr_at(step)


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.maximum(step, 1).astype(jnp.float32)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(
            s ** -0.5, s * self.warmup_steps ** -1.5)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries, self.values = list(boundaries), list(values)
        super().__init__(values[0], last_epoch, verbose)

    def lr_at(self, step):
        lr = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            lr = jnp.where(step < b, v, lr)
        return lr


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * (self.gamma ** step.astype(jnp.float32)
                               if hasattr(step, "astype") else self.gamma ** step)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr / (1 + self.gamma * step)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps, self.end_lr, self.power, self.cycle = \
            decay_steps, end_lr, power, cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        if self.cycle:
            decay = self.decay_steps * jnp.ceil(jnp.maximum(s, 1) / self.decay_steps)
        else:
            decay = self.decay_steps
            s = jnp.minimum(s, decay)
        frac = (1 - s / decay) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.peak = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps, self.start_lr, self.end_lr = warmup_steps, start_lr, end_lr
        super().__init__(end_lr if self.peak is None else self.peak, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            s, self.warmup_steps) / max(self.warmup_steps, 1)
        if self.inner is not None:
            after = self.inner.lr_at(jnp.maximum(step - self.warmup_steps, 0))
        else:
            after = jnp.asarray(self.peak, jnp.float32)
        return jnp.where(s < self.warmup_steps, warm, after)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (
            1 + jnp.cos(math.pi * jnp.minimum(s, self.T_max) / self.T_max))


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * self.gamma ** jnp.floor(
            jnp.asarray(step, jnp.float32) / self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        count = jnp.zeros((), jnp.float32)
        for m in self.milestones:
            count = count + (jnp.asarray(step) >= m)
        return self.base_lr * self.gamma ** count


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        if T_mult != 1:
            raise NotImplementedError("T_mult != 1 requires host-side state")
        self.T_0, self.eta_min = T_0, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.mod(jnp.asarray(step, jnp.float32), self.T_0)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (
            1 + jnp.cos(math.pi * s / self.T_0))


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.initial = max_learning_rate / divide_factor
        self.max_lr = max_learning_rate
        self.end_lr = end_learning_rate
        self.up_steps = int(total_steps * phase_pct)
        super().__init__(max_learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        up = self.initial + (self.max_lr - self.initial) * jnp.minimum(
            s, self.up_steps) / max(self.up_steps, 1)
        down_frac = jnp.clip((s - self.up_steps) /
                             max(self.total_steps - self.up_steps, 1), 0, 1)
        down = self.end_lr + (self.max_lr - self.end_lr) * 0.5 * (
            1 + jnp.cos(math.pi * down_frac))
        return jnp.where(s < self.up_steps, up, down)


class ReduceOnPlateau(LRScheduler):
    """Metric-driven; inherently host-side (matches reference semantics)."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0, verbose=False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.cooldown, self.min_lr = threshold, cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_left = 0
        self.current = learning_rate
        super().__init__(learning_rate)

    def lr_at(self, step):
        return jnp.asarray(self.current, jnp.float32)

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics)
        better = (self.best is None or
                  (m < self.best - self.threshold if self.mode == "min"
                   else m > self.best + self.threshold))
        if better:
            self.best, self.num_bad = m, 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.current = max(self.current * self.factor, self.min_lr)
                self.cooldown_left = self.cooldown
                self.num_bad = 0


class LinearLR(LRScheduler):
    """Reference: paddle.optimizer.lr.LinearLR — linear ramp from
    start_factor to end_factor over total_steps."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.start_factor, self.end_factor = start_factor, end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.minimum(jnp.asarray(step, jnp.float32), self.total_steps)
        f = self.start_factor + (self.end_factor - self.start_factor) * (
            s / max(self.total_steps, 1))
        return self.base_lr * f


class MultiplicativeDecay(LRScheduler):
    """Reference: paddle.optimizer.lr.MultiplicativeDecay — lr multiplied
    by lr_lambda(epoch) each step (cumulative product).

    ``lr_lambda`` is an arbitrary Python callable, so the cumulative
    product is precomputed ONCE (at construction) into a lookup table of
    ``max_steps`` entries; past the horizon the product continues with the
    table's last ratio (for the common constant-factor lambda this is
    exact at every step)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False, max_steps=10000):
        import numpy as np
        self.lr_lambda = lr_lambda
        self.max_steps = int(max_steps)
        factors = np.asarray([lr_lambda(i)
                              for i in range(1, self.max_steps + 1)],
                             np.float64)
        self._table = jnp.asarray(
            np.concatenate([[1.0], np.cumprod(factors)]), jnp.float32)
        self._last_ratio = float(factors[-1]) if len(factors) else 1.0
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.int32)
        idx = jnp.clip(s, 0, self.max_steps)
        over = jnp.maximum(s - self.max_steps, 0).astype(jnp.float32)
        return (self.base_lr * self._table[idx]
                * self._last_ratio ** over)


class CyclicLR(LRScheduler):
    """Reference: paddle.optimizer.lr.CyclicLR (triangular policy
    family)."""

    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down if step_size_down is not None else \
            step_size_up
        self.mode, self.exp_gamma = mode, exp_gamma
        # a user scale_fn overrides the built-in mode scaling (reference
        # semantics); it must be jnp-traceable (it receives a traced count)
        self.scale_fn, self.scale_mode = scale_fn, scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        total = self.up + self.down
        cycle = jnp.floor(1 + s / total)
        pos = s - (cycle - 1) * total
        frac = jnp.where(pos < self.up, pos / self.up,
                         1 - (pos - self.up) / self.down)
        amp = (self.max_lr - self.base_lr) * frac
        if self.scale_fn is not None:
            amp = amp * self.scale_fn(cycle if self.scale_mode == "cycle"
                                      else s)
        elif self.mode == "triangular2":
            amp = amp / (2.0 ** (cycle - 1))
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** s)
        return self.base_lr + amp


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
