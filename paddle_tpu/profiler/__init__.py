"""Profiler (``paddle.profiler`` parity over jax.profiler / XProf).

Reference (SURVEY.md §5.1): python/paddle/profiler/profiler.py — Profiler
with scheduler states (CLOSED/READY/RECORD), ``RecordEvent`` user scopes,
chrome-trace export, summary tables; C++ HostTracer + CUPTI device tracer.

TPU mapping: device-side timelines come from XLA via ``jax.profiler``
(xplane → TensorBoard/Perfetto — that's the CUPTI equivalent and needs no
code here beyond start/stop).  Host-side user scopes are recorded by
``RecordEvent`` (which *also* opens a ``jax.named_scope``+TraceAnnotation so
the same name shows up inside the device trace), and exported as a
chrome-trace JSON with a summary table, preserving the reference's
reporting surface.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from enum import Enum
from typing import Callable, Iterable, List, Optional, Tuple

import jax

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "active_profilers", "is_recording", "windowed_profiler"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last step of a record window


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class _HostEvent:
    __slots__ = ("name", "start_ns", "end_ns", "tid")

    def __init__(self, name, start_ns, end_ns, tid):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid


_active_profilers: List["Profiler"] = []
_lock = threading.Lock()


def active_profilers() -> List["Profiler"]:
    """Profilers between ``start()`` and ``stop()`` (any scheduler state).

    ``observability.span`` keys its chrome-trace bridge off this list —
    the same names flow to the always-on JSONL stream and the deep-dive
    trace (docs/OBSERVABILITY.md, "Trace spans")."""
    with _lock:
        return list(_active_profilers)


def is_recording() -> bool:
    """True while any active profiler is in a RECORD window."""
    with _lock:
        return any(p._recording for p in _active_profilers)


class RecordEvent:
    """User scope: ``with RecordEvent("forward"):``.  Recorded on the host
    timeline of every active profiler, and annotated into the device trace
    via jax's TraceAnnotation (named_scope)."""

    def __init__(self, name: str, event_type=None):
        del event_type  # API compat
        self.name = name
        self._scope = None
        self._t0 = 0

    def begin(self):
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        self._t0 = time.perf_counter_ns()

    def end(self):
        t1 = time.perf_counter_ns()
        if self._scope is not None:
            self._scope.__exit__(None, None, None)
            self._scope = None
        ev = _HostEvent(self.name, self._t0, t1, threading.get_ident())
        with _lock:
            for p in _active_profilers:
                if p._recording:
                    p._events.append(ev)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-indexed state machine, mirroring paddle.profiler.make_scheduler:
    ``skip_first`` steps CLOSED, then cycles of (closed, ready, record)."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback writing chrome-trace JSON into
    ``dir_name`` (reference: paddle.profiler.export_chrome_tracing)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{jax.process_index()}"
        path = os.path.join(dir_name, f"{name}_step{prof._step}.json")
        prof._export_chrome(path)
        return path

    return handler


class Profiler:
    """``paddle.profiler.Profiler`` parity.

    - host events from RecordEvent scopes (+ step markers from ``step()``)
    - device trace via jax.profiler start/stop into ``trace_dir`` (view with
      TensorBoard/XProf — the reference's timeline equivalent)
    - ``summary()`` prints an aggregated table of host scopes
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, timer_only: bool = False,
                 trace_dir: Optional[str] = None):
        del targets  # single-backend stack; accepted for API parity
        if isinstance(scheduler, tuple):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self._schedule = scheduler
        self._on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self._events: List[_HostEvent] = []
        self._step = 0
        self._step_t0: Optional[int] = None
        self._recording = False
        self._device_tracing = False
        self._state = ProfilerState.CLOSED
        # export dedupe: each record window fires on_trace_ready exactly
        # once.  Without this, a window ending in RECORD_AND_RETURN whose
        # next scheduled state is still recording (closed=0 back-to-back
        # cycles) was exported by step() AND re-exported by stop().
        self._window_exported = False

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with _lock:
            _active_profilers.append(self)
        self._apply_state(self._schedule(self._step) if self._schedule
                          else ProfilerState.RECORD)
        self._step_t0 = time.perf_counter_ns()
        return self

    def stop(self):
        # export only a window step() has not already exported (and that
        # has content): a stop() right after a RECORD_AND_RETURN boundary
        # used to re-fire on_trace_ready for the same window
        if self._recording and self._events and not self._window_exported \
                and self._on_trace_ready:
            self._on_trace_ready(self)
        self._apply_state(ProfilerState.CLOSED)
        with _lock:
            if self in _active_profilers:
                _active_profilers.remove(self)

    def step(self):
        """Mark a train-step boundary; advances the scheduler."""
        t1 = time.perf_counter_ns()
        if self._recording and self._step_t0 is not None:
            self._events.append(_HostEvent(f"ProfileStep#{self._step}",
                                           self._step_t0, t1, 0))
        fired = False
        if self._state == ProfilerState.RECORD_AND_RETURN and self._on_trace_ready:
            self._on_trace_ready(self)
            self._window_exported = True
            fired = True
        self._step += 1
        self._step_t0 = t1
        if self._schedule:
            self._apply_state(self._schedule(self._step))
        if fired and self._recording:
            # back-to-back record windows (closed=0 cycles): the exported
            # window's events must not leak into — and be re-exported
            # with — the next window
            self._events = []
            self._window_exported = False

    def _apply_state(self, state: ProfilerState):
        was_recording = self._recording
        self._state = state
        self._recording = state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        if not was_recording and self._recording:
            self._window_exported = False
        if self.timer_only:
            return
        want_device = self._recording and self.trace_dir is not None
        if want_device and not self._device_tracing:
            jax.profiler.start_trace(self.trace_dir)
            self._device_tracing = True
        elif not want_device and self._device_tracing:
            jax.profiler.stop_trace()
            self._device_tracing = False
        if self._recording and not was_recording:
            self._events = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ---------------------------------------------------------

    def _export_chrome(self, path: str):
        events = []
        for ev in self._events:
            events.append({"name": ev.name, "ph": "X", "pid": os.getpid(),
                           "tid": ev.tid, "ts": ev.start_ns / 1e3,
                           "dur": (ev.end_ns - ev.start_ns) / 1e3})
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return path

    def export(self, path: str, format: str = "json"):
        if format != "json":
            raise ValueError("only chrome-trace json export is supported")
        return self._export_chrome(path)

    def aggregate(self) -> List[Tuple[str, int, float, float]]:
        """[(name, count, total_ms, mean_ms)] sorted by total time."""
        acc: dict = defaultdict(lambda: [0, 0])
        for ev in self._events:
            a = acc[ev.name]
            a[0] += 1
            a[1] += ev.end_ns - ev.start_ns
        rows = [(n, c, t / 1e6, t / 1e6 / c) for n, (c, t) in acc.items()]
        return sorted(rows, key=lambda r: -r[2])

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms") -> str:
        del sorted_by, op_detail, thread_sep, time_unit
        rows = self.aggregate()
        w = max([len(r[0]) for r in rows] + [10])
        lines = [f"{'Name':<{w}}  {'Calls':>6}  {'Total(ms)':>10}  {'Avg(ms)':>10}",
                 "-" * (w + 32)]
        for n, c, tot, avg in rows:
            lines.append(f"{n:<{w}}  {c:>6}  {tot:>10.3f}  {avg:>10.3f}")
        table = "\n".join(lines)
        print(table)
        return table


def windowed_profiler(trace_dir: str, steps: Optional[int] = None,
                      on_trace_ready=None) -> Profiler:
    """A STARTED :class:`Profiler` recording host scopes + the device
    trace (``jax.profiler`` start/stop) into ``trace_dir`` — the
    bounded-capture entry the SLO-triggered capture arms
    (``observability.trace.SLOCapture``): the caller advances it with
    ``step()`` and ``stop()``s it after its window.  With ``steps``
    given, a ``make_scheduler`` window additionally closes the device
    trace on its own after that many ``step()`` calls (``stop()`` is
    still required to flush the host events / deregister)."""
    os.makedirs(trace_dir, exist_ok=True)
    sched = None
    if steps is not None:
        sched = make_scheduler(closed=0, ready=0, record=int(steps),
                               repeat=1)
    return Profiler(scheduler=sched, on_trace_ready=on_trace_ready,
                    trace_dir=trace_dir).start()


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)
