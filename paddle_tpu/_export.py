"""Public-namespace hygiene: ``__all__`` builder.

The reference never re-exports its implementation imports (``paddle.nn.
functional`` has no ``paddle.nn.functional.paddle`` attribute); a module
here that does ``import jax`` without an ``__all__`` leaks ``jax`` into
``from paddle_tpu.x import *`` and into API-surface probes.  Modules call
``__all__ = public_all(globals())`` as their last statement: every public
global EXCEPT foreign (non-paddle_tpu) modules.  ``check_api_compat``
enforces the invariant — a foreign module reachable as a public attribute
fails the gate.
"""

from __future__ import annotations

import types


def is_foreign_module(v) -> bool:
    """A module object that is not part of the paddle_tpu package tree —
    the one kind of public attribute the reference never exposes.  The
    single definition of the invariant; ``check_api_compat`` and
    ``api_probe`` import it rather than re-deriving it."""
    return isinstance(v, types.ModuleType) \
        and not (v.__name__ + ".").startswith("paddle_tpu.")


def public_all(g: dict) -> list:
    return sorted(n for n, v in g.items()
                  if not n.startswith("_") and not is_foreign_module(v))
