"""paddle_tpu.audio.datasets — reference: python/paddle/audio/datasets/
(TESS, ESC50).

Zero-egress environment: datasets read from a local ``data_dir`` laid out
as the upstream archives extract (no downloads); a missing directory
raises with the expected layout in the message.
"""

from __future__ import annotations

import os

from ..io import Dataset
from . import backends


class _FolderAudioDataset(Dataset):
    """Audio files under class-encoding filenames, label parsed per
    subclass rule."""

    def __init__(self, data_dir, mode="train", feat_type="raw", **kw):
        if not data_dir or not os.path.isdir(data_dir):
            raise FileNotFoundError(
                f"{type(self).__name__}: pass data_dir pointing at the "
                f"extracted archive (downloads are disabled in this "
                f"environment); got {data_dir!r}")
        self.mode = mode
        self.feat_type = feat_type
        self.files, self.labels = self._index(data_dir)

    def _index(self, data_dir):
        raise NotImplementedError

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        wav, sr = backends.load(self.files[idx])
        return wav, self.labels[idx]


class TESS(_FolderAudioDataset):
    """Toronto Emotional Speech Set: WAV files named *_<emotion>.wav in
    per-speaker folders."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def _index(self, data_dir):
        files, labels = [], []
        for root, _, names in sorted(os.walk(data_dir)):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                emo = n.rsplit("_", 1)[-1][:-4].lower()
                if emo in self.EMOTIONS:
                    files.append(os.path.join(root, n))
                    labels.append(self.EMOTIONS.index(emo))
        return files, labels


class ESC50(_FolderAudioDataset):
    """ESC-50 environmental sounds: files named F-C-T-L.wav where L is
    the class id; fold F==5 is the validation split."""

    def _index(self, data_dir):
        files, labels = [], []
        want_valid = self.mode != "train"
        for root, _, names in sorted(os.walk(data_dir)):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                parts = n[:-4].split("-")
                if len(parts) != 4:
                    continue
                fold, label = int(parts[0]), int(parts[3])
                if (fold == 5) == want_valid:
                    files.append(os.path.join(root, n))
                    labels.append(label)
        return files, labels


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
