"""paddle_tpu.audio.features — reference:
python/paddle/audio/features/layers.py (the feature-extraction Layers)."""

from . import (MFCC, LogMelSpectrogram, MelSpectrogram,  # noqa: F401
               Spectrogram)
