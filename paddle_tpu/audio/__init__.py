"""``paddle.audio`` parity (minimal): STFT spectrogram + mel features.

Reference: python/paddle/audio/ (functional/window.py, features/layers.py).
Capability-parity tier per SURVEY §2.6 (low priority); the compute-relevant
pieces (stft via ops.fft, mel filterbank matmul) are here and jit-safe.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

__all__ = ["get_window", "stft", "spectrogram", "mel_frequencies",
           "compute_fbank_matrix", "Spectrogram", "MelSpectrogram"]


def get_window(window: str, win_length: int, fftbins: bool = True):
    n = win_length
    k = jnp.arange(n)
    denom = n if fftbins else n - 1
    if window in ("hann", "hanning"):
        return 0.5 - 0.5 * jnp.cos(2 * math.pi * k / denom)
    if window == "hamming":
        return 0.54 - 0.46 * jnp.cos(2 * math.pi * k / denom)
    if window in ("rect", "boxcar", "ones"):
        return jnp.ones(n)
    raise ValueError(f"unsupported window {window!r}")


def stft(x, n_fft=512, hop_length=None, win_length=None, window="hann",
         center=True):
    """x: (..., T) → complex (..., n_fft//2+1, frames)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if wl > n_fft:
        raise ValueError(f"win_length ({wl}) must be <= n_fft ({n_fft})")
    win = get_window(window, wl)
    if wl < n_fft:
        pad = (n_fft - wl) // 2
        win = jnp.pad(win, (pad, n_fft - wl - pad))
    if center:
        pad_cfg = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad_cfg, mode="reflect")
    t = x.shape[-1]
    n_frames = 1 + (t - n_fft) // hop
    idx = (jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None])
    frames = x[..., idx] * win          # (..., frames, n_fft)
    spec = jnp.fft.rfft(frames, axis=-1)
    return jnp.swapaxes(spec, -1, -2)   # (..., bins, frames)


def spectrogram(x, n_fft=512, hop_length=None, power=2.0, **kw):
    s = jnp.abs(stft(x, n_fft=n_fft, hop_length=hop_length, **kw))
    return s ** power


def mel_frequencies(n_mels, f_min, f_max):
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mels = np.linspace(hz_to_mel(f_min), hz_to_mel(f_max), n_mels)
    return mel_to_hz(mels)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None):
    f_max = f_max or sr / 2
    fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max)
    fb = np.zeros((n_mels, len(fft_freqs)), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = mel_f[i], mel_f[i + 1], mel_f[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-8)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-8)
        fb[i] = np.maximum(0, np.minimum(up, down))
    return jnp.asarray(fb)


class Spectrogram:
    def __init__(self, n_fft=512, hop_length=None, power=2.0,
                 window="hann"):
        self.kw = dict(n_fft=n_fft, hop_length=hop_length, power=power,
                       window=window)

    def __call__(self, x):
        return spectrogram(x, **self.kw)


class MelSpectrogram:
    def __init__(self, sr=16000, n_fft=512, hop_length=None, n_mels=64,
                 f_min=0.0, f_max=None, power=2.0):
        self.spec = Spectrogram(n_fft, hop_length, power)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max)

    def __call__(self, x):
        s = self.spec(x)                       # (..., bins, frames)
        return jnp.einsum("mb,...bf->...mf", self.fbank, s)


def power_to_db(x, ref=1.0, amin=1e-10, top_db=80.0):
    """Reference: paddle.audio.features (librosa-compatible dB scaling)."""
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """Type-II DCT matrix (n_mels, n_mfcc) — the MFCC projection."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)          # (n_mfcc, n_mels)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return jnp.asarray(dct.T.astype(np.float32))             # (n_mels, n_mfcc)


class LogMelSpectrogram:
    """Reference: paddle.audio.features.LogMelSpectrogram."""

    def __init__(self, sr=16000, n_fft=512, hop_length=None, n_mels=64,
                 f_min=0.0, f_max=None, power=2.0, ref_value=1.0,
                 amin=1e-10, top_db=None):
        self.mel = MelSpectrogram(sr, n_fft, hop_length, n_mels, f_min,
                                  f_max, power)
        self.ref, self.amin, self.top_db = ref_value, amin, top_db

    def __call__(self, x):
        return power_to_db(self.mel(x), self.ref, self.amin, self.top_db)


class MFCC:
    """Reference: paddle.audio.features.MFCC — log-mel → DCT-II cepstra."""

    def __init__(self, sr=16000, n_mfcc=40, n_fft=512, hop_length=None,
                 n_mels=64, f_min=0.0, f_max=None, top_db=None):
        if n_mfcc > n_mels:
            raise ValueError(f"n_mfcc ({n_mfcc}) must be <= n_mels ({n_mels})")
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, n_mels,
                                        f_min, f_max, top_db=top_db)
        self.dct = create_dct(n_mfcc, n_mels)

    def __call__(self, x):
        lm = self.logmel(x)                     # (..., mels, frames)
        return jnp.einsum("mk,...mf->...kf", self.dct, lm)


__all__ += ["power_to_db", "create_dct", "LogMelSpectrogram", "MFCC"]


# paddle.audio submodule structure (reference: python/paddle/audio/)
from . import backends  # noqa: E402,F401
from . import features  # noqa: E402,F401
from . import functional  # noqa: E402,F401
from . import datasets  # noqa: E402,F401
from .backends import info, load, save  # noqa: E402,F401

__all__ += ["backends", "features", "functional", "datasets", "info",
            "load", "save"]
