"""paddle_tpu.audio.backends — WAV IO on the Python stdlib.

Reference: python/paddle/audio/backends/ (soundfile/wave backends).  The
stdlib ``wave`` backend covers PCM WAV load/save/info with zero extra
dependencies; other formats raise with a clear message.
"""

from __future__ import annotations

import wave

import numpy as np


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def set_backend(backend_name: str):
    if backend_name not in ("wave",):
        raise ValueError("only the stdlib 'wave' backend is available "
                         "(PCM WAV); transcode other formats on the "
                         "dataloader side")


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         8 * w.getsampwidth())


def load(filepath: str, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (tensor, sample_rate); float32 in [-1, 1] when normalize."""
    import jax.numpy as jnp
    with wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(int(frame_offset))
        count = n - int(frame_offset) if num_frames < 0 else int(num_frames)
        raw = w.readframes(count)
    if width == 3:
        # 24-bit PCM: unpack 3-byte little-endian signed ints
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
        data = (b[:, 0].astype(np.int32)
                | (b[:, 1].astype(np.int32) << 8)
                | (b[:, 2].astype(np.int32) << 16))
        data = np.where(data >= 1 << 23, data - (1 << 24), data)
        data = data.reshape(-1, ch)
    elif width in (1, 2, 4):
        dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype).reshape(-1, ch)
    else:
        raise ValueError(f"audio.load: unsupported PCM sample width "
                         f"{width * 8} bits (supported: 8/16/24/32)")
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    out = data.T if channels_first else data
    return jnp.asarray(out), sr


def save(filepath: str, src, sample_rate: int, channels_first=True,
         bits_per_sample=16):
    if bits_per_sample != 16:
        raise ValueError("wave backend writes 16-bit PCM")
    data = np.asarray(src)
    if channels_first:
        data = data.T
    if data.dtype.kind == "f":
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as w:
        w.setnchannels(data.shape[1] if data.ndim > 1 else 1)
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(data.astype(np.int16).tobytes())


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
