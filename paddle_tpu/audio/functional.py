"""paddle_tpu.audio.functional — reference:
python/paddle/audio/functional/ (window/fbank/dct/db helpers)."""

from . import (compute_fbank_matrix, create_dct,  # noqa: F401
               get_window, mel_frequencies, power_to_db)


def hz_to_mel(freq, htk=False):
    """Reference: paddle.audio.functional.hz_to_mel (Slaney by default)."""
    import numpy as np
    f = np.asarray(freq, np.float64)
    if htk:
        return 2595.0 * np.log10(1.0 + f / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk=False):
    import numpy as np
    m = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def fft_frequencies(sr, n_fft):
    import numpy as np
    return np.linspace(0, sr / 2.0, 1 + n_fft // 2)
