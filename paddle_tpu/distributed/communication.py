"""Collective communication API (``paddle.distributed.*`` parity).

Reference: python/paddle/distributed/communication/{all_reduce,all_gather,
reduce_scatter,all_to_all,broadcast,...}.py over C++ ProcessGroupNCCL
(paddle/fluid/distributed/collective/process_group_nccl.cc).

TPU redesign (SURVEY.md §5.8): there is no user-space communicator.  A
"group" is a set of mesh axis names.  Two call modes:

- **Inside shard_map/pjit-manual regions** (the hot path — pipeline bodies,
  MoE dispatch, ring attention): these functions lower directly to
  ``lax.psum/all_gather/psum_scatter/all_to_all/ppermute`` on ICI.
- **Eager on global arrays** (debug/occasional): the call wraps itself in a
  tiny jitted ``shard_map`` over the active mesh.

ProcessGroup-task semantics (async handles, streams) dissolve: XLA's
latency-hiding scheduler overlaps collectives with compute automatically.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..core.compat import shard_map

from . import fleet


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A named subset of mesh axes (the reference's ProcessGroup handle)."""

    def __init__(self, axes: Union[str, Sequence[str]], mesh: Optional[Mesh] = None):
        self.axes = (axes,) if isinstance(axes, str) else tuple(axes)
        self._mesh = mesh

    @property
    def mesh(self) -> Mesh:
        if self._mesh is not None:
            return self._mesh
        hcg = fleet.get_hybrid_communicate_group()
        if hcg is None:
            raise RuntimeError("no mesh: call fleet.init or pass mesh=")
        return hcg.mesh

    @property
    def nranks(self) -> int:
        m = self.mesh
        n = 1
        for a in self.axes:
            n *= m.shape[a]
        return n

    # paddle Group API parity
    @property
    def world_size(self):
        return self.nranks


def new_group(axes="dp", mesh=None) -> Group:
    """Reference: paddle.distributed.new_group(ranks).  Groups are axis
    subsets, not rank lists — rank lists don't survive SPMD compilation."""
    return Group(axes, mesh)


def _axis_tuple(group):
    if group is None:
        hcg = fleet.get_hybrid_communicate_group()
        if hcg is None:
            return None
        axes = tuple(hcg.active_axes())
        return axes if axes else None
    return group.axes if isinstance(group, Group) else (
        (group,) if isinstance(group, str) else tuple(group))


def _axis_bound(axes) -> bool:
    """True when ``axes`` are bound in the current trace (inside shard_map)."""
    try:
        for a in axes:
            jax.lax.axis_index(a)
        return True
    except Exception:
        return False


def _eager_wrap(fn, tensor, axes, out_specs_fn=None, in_spec=None):
    """Run a collective on a global array by shard_mapping it over ``axes``."""
    mesh = Group(axes).mesh
    in_spec = in_spec if in_spec is not None else P(axes)
    out_spec = out_specs_fn(in_spec) if out_specs_fn else in_spec
    # check_vma off: older jax cannot infer replication through tiled
    # all_gather/psum_scatter bodies and rejects the P() out_specs the
    # replicated-in/replicated-out eager contract uses
    f = shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                  check_vma=False)
    return f(tensor)


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def _reduce(x, op, axes):
    if op in (ReduceOp.SUM, "sum"):
        return jax.lax.psum(x, axes)
    if op in (ReduceOp.AVG, "avg"):
        n = 1
        for a in axes:
            n = n * jax.lax.psum(1, a)
        return jax.lax.psum(x, axes) / n
    if op in (ReduceOp.MAX, "max"):
        return jax.lax.pmax(x, axes)
    if op in (ReduceOp.MIN, "min"):
        return jax.lax.pmin(x, axes)
    if op in (ReduceOp.PROD, "prod"):
        return jnp.exp(jax.lax.psum(jnp.log(x), axes))
    raise ValueError(f"unknown reduce op {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """SUM/MAX/MIN/PROD all-reduce over the group's axes."""
    axes = _axis_tuple(group)
    if axes is None:
        return tensor
    if _axis_bound(axes):
        return _reduce(tensor, op, axes)
    # eager: replicated-in, replicated-out
    return _eager_wrap(lambda x: _reduce(x, op, axes), tensor, axes,
                       in_spec=P(), out_specs_fn=lambda s: P())


def all_gather(tensor_or_list, tensor=None, group=None, axis=0, sync_op=True):
    """paddle signature: all_gather(tensor_list, tensor, group) — also
    usable functionally: gathered = all_gather(tensor, group=g)."""
    out_list = None
    if isinstance(tensor_or_list, list):
        out_list, x = tensor_or_list, tensor
    else:
        x = tensor_or_list
    axes = _axis_tuple(group)
    if axes is None:
        res = x
    elif _axis_bound(axes):
        res = x
        for a in axes[::-1]:
            res = jax.lax.all_gather(res, a, axis=axis, tiled=True)
    else:
        res = _eager_wrap(
            lambda v: jax.lax.all_gather(v, axes[0] if len(axes) == 1 else axes,
                                         axis=axis, tiled=True),
            x, axes, in_spec=P(), out_specs_fn=lambda s: P())
    if out_list is not None:
        n = Group(axes).nranks if axes else 1
        out_list.extend(jnp.split(res, n, axis=axis))
        return out_list
    return res


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, axis=0, sync_op=True):
    axes = _axis_tuple(group)
    if axes is None:
        return tensor
    if _axis_bound(axes):
        res = tensor
        for a in axes:
            res = jax.lax.psum_scatter(res, a, scatter_dimension=axis, tiled=True)
        return res
    return _eager_wrap(
        lambda v: jax.lax.psum_scatter(v, axes[0], scatter_dimension=axis,
                                       tiled=True),
        tensor, axes, in_spec=P(), out_specs_fn=lambda s: P(*(
            [axes[0] if i == axis else None for i in range(tensor.ndim)])))


def alltoall(tensor, group=None, split_axis=0, concat_axis=0, sync_op=True):
    """all_to_all: scatter ``split_axis``, gather ``concat_axis``."""
    axes = _axis_tuple(group)
    if axes is None:
        return tensor
    a = axes[0]
    if _axis_bound(axes):
        return jax.lax.all_to_all(tensor, a, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
    return _eager_wrap(
        lambda v: jax.lax.all_to_all(v, a, split_axis=split_axis,
                                     concat_axis=concat_axis, tiled=True),
        tensor, axes,
        in_spec=P(*([a if i == concat_axis else None for i in range(tensor.ndim)])),
        out_specs_fn=lambda s: P(*([a if i == split_axis else None
                                    for i in range(tensor.ndim)])))


alltoall_single = alltoall


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Broadcast from ``src`` rank of the group axis.

    SPMD note: under jit all ranks hold the same global value already; the
    explicit form matters inside shard_map, where we select src's shard and
    psum-mask it across the axis.
    """
    axes = _axis_tuple(group)
    if axes is None or not _axis_bound(axes):
        return tensor  # global arrays are already consistent
    a = axes[0]
    idx = jax.lax.axis_index(a)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return jax.lax.psum(masked, a)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    axes = _axis_tuple(group)
    if axes is None:
        return tensor
    if _axis_bound(axes):
        red = _reduce(tensor, op, axes)
        idx = jax.lax.axis_index(axes[0])
        return jnp.where(idx == dst, red, tensor)
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Scatter: rank i of the group axis receives element i.

    Shape contract differs by execution mode (inherent to SPMD):
    - inside shard_map (axis bound): returns the LOCAL element, shape
      ``rest`` — the reference's per-rank view;
    - eager on global arrays: a per-rank-different value can only exist as
      a sharded GLOBAL array, so the result keeps the leading group dim,
      shape ``(n, *rest)`` sharded over the axis (rank i's addressable
      shard is its element).
    """
    axes = _axis_tuple(group)
    if axes is None:
        return tensor
    a = axes[0]
    if tensor_list is not None:
        stacked = jnp.stack(tensor_list, axis=0)
    else:
        stacked = tensor
    if _axis_bound(axes):
        idx = jax.lax.axis_index(a)
        return jax.lax.dynamic_index_in_dim(stacked, idx, axis=0, keepdims=False)
    # eager on global arrays: a per-rank-different result IS a sharded array —
    # return ``stacked`` sharded over the axis on dim 0 (rank i's shard is
    # its scattered value); src is irrelevant since global values agree
    mesh = Group(axes).mesh
    return jax.device_put(stacked, NamedSharding(mesh, P(a)))


def send(tensor, dst, group=None):
    """P2P send — see ``p2p_shift``; raw send/recv don't exist under SPMD."""
    raise NotImplementedError(
        "SPMD has no raw send/recv; use distributed.p2p_shift(x, offset, axis) "
        "(ppermute) — the pipeline scheduler uses that internally")


recv = send


def p2p_shift(tensor, offset=1, axis="pp"):
    """Rotate values along a mesh axis ring (ppermute): rank i -> i+offset.

    The building block that replaces the reference's batched send/recv
    (p2p_communication.py) for pipeline and ring attention.
    """
    n = jax.lax.psum(1, axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return jax.lax.ppermute(tensor, axis, perm)


def isend(tensor, dst=None, group=None):
    """Marker for ``P2POp``/``batch_isend_irecv`` (reference:
    paddle.distributed.isend). Standalone use has no SPMD meaning — batch
    matched pairs instead."""
    raise NotImplementedError(
        "use P2POp(isend, t, peer_offset=k) + batch_isend_irecv([...]); "
        "a lone isend has no SPMD analogue")


def irecv(tensor=None, src=None, group=None):
    """Marker for ``P2POp``/``batch_isend_irecv`` (reference irecv)."""
    raise NotImplementedError(
        "use P2POp(irecv, buf, peer_offset=-k) + batch_isend_irecv([...])")


class P2POp:
    """One half of a matched P2P exchange (reference:
    paddle.distributed.P2POp(op, tensor, peer) in batch_isend_irecv.py).

    SPMD deviation, documented: peers are **relative ring offsets**
    (``peer_offset=+1`` = next rank on the axis), not absolute ranks —
    under one traced program every rank runs the same op list, so the
    pattern must be rank-uniform, which is exactly how the reference's
    pipeline p2p layer uses the API (send next / recv prev).
    """

    def __init__(self, op, tensor, peer_offset=None, group=None, peer=None):
        if op not in (isend, irecv):
            raise ValueError("op must be distributed.isend or distributed.irecv")
        if peer_offset is None:
            raise ValueError(
                "SPMD P2POp needs peer_offset=(peer_rank - my_rank) mod n; "
                "absolute `peer` ranks are not resolvable inside one traced "
                "program")
        self.op, self.tensor, self.group = op, tensor, group
        self.peer_offset = int(peer_offset)


class P2PTask:
    """Completed-exchange handle (reference returns async tasks; XLA
    schedules the collective, so wait() just hands back the result)."""

    def __init__(self, result):
        self.result = result

    def wait(self):
        return self.result


def batch_isend_irecv(op_list):
    """Execute matched isend/irecv pairs as ppermutes (reference:
    batch_isend_irecv → ncclGroupStart/End batched send/recv).

    Every ``irecv`` with ``peer_offset=-k`` is fulfilled by the ``isend``
    with ``peer_offset=+k`` (same |offset| = one ring ppermute, which is
    how XLA expresses the batched NCCL pair). Returns one ``P2PTask`` per
    op in order: isend tasks carry None, irecv tasks carry the received
    tensor.
    """
    def _gkey(op):
        axes = _axis_tuple(op.group)
        return axes if axes is not None else ("pp",)

    sends = {}
    for op in op_list:
        if op.op is isend:
            key = (_gkey(op), op.peer_offset)
            if key in sends:
                raise ValueError(
                    f"duplicate isend offset {op.peer_offset} on group "
                    f"axes {key[0]}")
            sends[key] = op
    matched = set()
    tasks = []
    for op in op_list:
        if op.op is isend:
            tasks.append(P2PTask(None))
            continue
        k = -op.peer_offset  # recv-from -k pairs with send-to +k
        key = (_gkey(op), k)
        src = sends.get(key)
        if src is None:
            raise ValueError(
                f"irecv(peer_offset={op.peer_offset}) has no matching "
                f"isend(peer_offset={k}) on group axes {key[0]}")
        matched.add(key)
        a = key[0][0]
        if _axis_bound(key[0]):
            tasks.append(P2PTask(p2p_shift(src.tensor, k, a)))
        else:
            # eager on global arrays: dim 0 is the rank dim (same
            # convention as scatter's eager path) — ring shift = roll
            tasks.append(P2PTask(jnp.roll(src.tensor, k, axis=0)))
    unmatched = set(sends) - matched
    if unmatched:
        raise ValueError(
            "isend ops with no matching irecv in the batch (the send "
            f"would silently vanish): {sorted((g, o) for g, o in unmatched)}")
    return tasks


def barrier(group=None):
    jax.block_until_ready(jnp.zeros(()))


def get_rank(group=None) -> int:
    if group is not None:
        hcg = fleet.get_hybrid_communicate_group()
        if hcg is not None:
            ax = _axis_tuple(group)[0]
            return hcg._rank_in(ax)
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        g = group if isinstance(group, Group) else Group(_axis_tuple(group))
        return g.nranks
    return jax.process_count()


_parallel_env_initialized = False


def is_initialized() -> bool:
    """True once the parallel environment exists — either ``fleet.init``
    built a hybrid group or ``init_parallel_env`` ran (reference:
    paddle.distributed.is_initialized, truthful-before-init)."""
    return (_parallel_env_initialized
            or fleet.get_hybrid_communicate_group() is not None)


def init_parallel_env(cluster_env: Optional[dict] = None):
    """Reference: paddle.distributed.init_parallel_env → TCPStore + NCCL
    init.  TPU: multi-host bootstrap via the jax coordination service; on a
    single host this is a no-op."""
    import os
    if cluster_env or os.environ.get("PDTPU_COORDINATOR"):
        env = cluster_env or {}
        jax.distributed.initialize(
            coordinator_address=env.get("coordinator",
                                        os.environ.get("PDTPU_COORDINATOR")),
            num_processes=int(env.get("num_processes",
                                      os.environ.get("PDTPU_NUM_PROCESSES", 1))),
            process_id=int(env.get("process_id",
                                   os.environ.get("PDTPU_PROCESS_ID", 0))))
    global _parallel_env_initialized
    _parallel_env_initialized = True
    return None


# ---------------------------------------------------------------------------
# collective-consistency watchdog + telemetry hooks (SURVEY §5.2/§5.5):
# when debug.collective_debug() is active, every collective issued through
# this module is recorded for cross-rank sequence verification; when
# observability is enabled, byte/call counters are routed into the metrics
# registry.  Both hooks are one falsy check when off.
# ---------------------------------------------------------------------------

import functools as _functools

from . import debug as _debug
from ..observability import _state as _obs_state
from ..observability.spans import span as _span, spans_active as _spans_active
from ..resilience import _state as _rs_state


def _traced(fn, name):
    @_functools.wraps(fn)
    def wrapper(tensor, *a, **kw):
        # fault-injection site "collective": one falsy check when no
        # injector is installed (resilience/_state.py contract)
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            fi("collective")
        rec = _obs_state.COLLECTIVE[0]
        tracing = _debug.get_trace() is not None
        label = None
        if tracing or rec is not None:
            grp = kw.get("group", kw.get("axis"))
            axes = _axis_tuple(grp) if not isinstance(grp, str) else (grp,)
            label = ",".join(axes) if axes else "world"
            if tracing:
                _debug.record(name, axes or ("world",),
                              getattr(tensor, "shape", None),
                              getattr(tensor, "dtype", None))
            if rec is not None:
                payload = tensor
                if isinstance(tensor, list) and not tensor:
                    # paddle-style all_gather(tensor_list, tensor, ...):
                    # the first positional is the (empty) OUTPUT list —
                    # the payload is the second argument
                    payload = a[0] if a else kw.get("tensor", tensor)
                rec(name, axes, payload)
        # span OUTSIDE the hook gates (ckpt-style): the span_begin
        # breadcrumb lands in the flight recorder BEFORE the collective
        # blocks — so a wedged collective is the last thing a hang dump
        # shows even with collectives=False — and the profiler bridge
        # works without telemetry.  Same once-per-trace caveat as the
        # byte counters for calls inside jit.  The spans_active() fast
        # path keeps the fully-disabled cost at two falsy checks (no
        # span or f-string construction).
        if not _spans_active():
            return fn(tensor, *a, **kw)
        with _span(f"collective.{name}", axes=label):
            return fn(tensor, *a, **kw)
    return wrapper


for _n in ("all_reduce", "all_gather", "reduce_scatter", "alltoall",
           "alltoall_single", "broadcast", "reduce", "scatter", "p2p_shift",
           "batch_isend_irecv"):
    if _n in globals():
        globals()[_n] = _traced(globals()[_n], _n)
del _n
