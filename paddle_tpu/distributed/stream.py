"""``paddle.distributed.stream`` parity surface.

Reference: python/paddle/distributed/communication/stream/ — collective
variants taking an explicit comm stream (``sync_op``/``use_calc_stream``)
for manual comm/compute overlap on CUDA.

TPU redesign: XLA's latency-hiding scheduler owns stream placement — there
is no user-visible comm stream to select, and overlap happens by compiler
scheduling (SURVEY §5.8). These wrappers accept and ignore the stream
knobs so reference training scripts port unchanged; semantics equal the
plain collectives.
"""

from __future__ import annotations

import functools
import inspect

from . import communication as _comm


def _stream_variant(fn):
    # In the reference these knobs are the TRAILING positional-or-keyword
    # params; drop them however they're passed (extra trailing positionals
    # included) so ported call sites work verbatim.
    n_pos = len([p for p in inspect.signature(fn).parameters.values()
                 if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)])

    @functools.wraps(fn)
    def wrapper(*args, sync_op=True, use_calc_stream=False, **kwargs):
        del sync_op, use_calc_stream  # XLA schedules streams (see module doc)
        if len(args) > n_pos:
            args = args[:n_pos]   # trailing stream knobs passed positionally
        return fn(*args, **kwargs)

    return wrapper


all_reduce = _stream_variant(_comm.all_reduce)
all_gather = _stream_variant(_comm.all_gather)
reduce_scatter = _stream_variant(_comm.reduce_scatter)
alltoall = _stream_variant(_comm.alltoall)
alltoall_single = _stream_variant(_comm.alltoall_single)
broadcast = _stream_variant(_comm.broadcast)
reduce = _stream_variant(_comm.reduce)
scatter = _stream_variant(_comm.scatter)
send = _stream_variant(_comm.send)
recv = _stream_variant(_comm.recv)
