"""``paddle_tpu.distributed`` — hybrid parallelism over TPU meshes.

Subsystem map (reference SURVEY.md §2.4/2.5):
- fleet: topology/strategy orchestration (fleet.init + hybrid_configs)
- communication: collective API (all_reduce/.../p2p_shift) over mesh axes
- mp_layers: tensor-parallel layers + Megatron-SP
- pipeline: 1F1B/GPipe pipeline parallel via shard_map + ppermute
- sharding: ZeRO stage 1/2/3 semantics (group_sharded_parallel)
- moe: expert parallel MoE layer (all_to_all dispatch)
- cp: context parallelism (Ulysses all_to_all + ring attention)
- auto: shard_tensor / reshard (auto-parallel DistTensor parity)
"""

from . import fleet  # noqa: F401
from .topology import AXIS_ORDER, HybridCommunicateGroup, HybridTopology  # noqa: F401
from .communication import (ReduceOp, Group, new_group, all_reduce,  # noqa: F401
                            all_gather, reduce_scatter, alltoall,
                            alltoall_single, broadcast, reduce, scatter,
                            send, recv, isend, irecv, P2POp, P2PTask,
                            batch_isend_irecv, p2p_shift, barrier, get_rank,
                            get_world_size, is_initialized,
                            init_parallel_env)
from .mp_layers import (ColumnParallelLinear, RowParallelLinear,  # noqa: F401
                        VocabParallelEmbedding, ParallelCrossEntropy,
                        ColumnSequenceParallelLinear,
                        RowSequenceParallelLinear,
                        scatter_to_sequence_parallel,
                        gather_from_sequence_parallel,
                        mark_as_sequence_parallel_parameter)
from .auto import (DistAttr, Partial, PartialTensor,  # noqa: F401
                   ProcessMesh, Replicate, Shard, ShardDataloader,
                   dtensor_from_fn, reshard, shard_dataloader, shard_layer,
                   shard_tensor)
from .parallel import DataParallel  # noqa: F401
from .engine import DistModel, Engine, to_static  # noqa: F401
from .recompute import recompute, RecomputeWrapper  # noqa: F401
from .pipeline import (LayerDesc, SharedLayerDesc, PipelineLayer,  # noqa: F401
                       PipelineParallel, StackedPipelineStages)
from . import sharding  # noqa: F401
from .sharding import group_sharded_parallel  # noqa: F401
from . import moe  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import cp  # noqa: F401
from .cp import (ring_attention, ulysses_attention,  # noqa: F401
                 context_parallel_attention)
from .spawn import spawn  # noqa: F401
from . import rpc  # noqa: F401
from . import ps  # noqa: F401
from . import stream  # noqa: F401

# paddle.distributed.save_state_dict / load_state_dict parity (reference:
# python/paddle/distributed/checkpoint/) — implemented in paddle_tpu.ckpt
# with cross-topology reshard-on-load
from ..ckpt import load_state_dict, save_state_dict  # noqa: F401

# round-4 tail: object collectives, gloo host group, ParallelEnv,
# Placement, split/shard_optimizer/unshard_dtensor — see misc.py
from .misc import (  # noqa: F401
    ParallelEnv, Placement, Strategy, all_gather_object,
    broadcast_object_list, destroy_process_group, get_backend, get_group,
    gloo_barrier, gloo_init_parallel_env, gloo_release, is_available,
    scatter_object_list, shard_optimizer, split, unshard_dtensor, wait)


def __getattr__(name):
    if name == "checkpoint":  # paddle.distributed.checkpoint module alias
        from .. import ckpt
        return ckpt
    if name == "launch":  # paddle.distributed.launch module alias
        from .. import launch
        return launch
    raise AttributeError(f"module 'paddle_tpu.distributed' has no attribute {name!r}")


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()
