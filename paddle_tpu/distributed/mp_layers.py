"""Tensor-parallel layers (Megatron-style).

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding) and
mp_ops.py (_c_identity/mp_allreduce autograd ops), plus the vocab-parallel
loss kernel c_softmax_with_cross_entropy.

TPU redesign: the reference hand-writes the collective choreography
(identity-forward/allreduce-backward, allreduce after RowParallel) as custom
autograd ops.  Under GSPMD the same physics falls out of sharding
annotations: the weight carries a PartitionSpec over the ``mp`` axis, the
activation carries a sharding constraint, and XLA inserts exactly the
all-reduce/all-gather the reference codes by hand — including their
transposes in backward.  These layers therefore reduce to (a) partitioned
parameter creation, (b) the right ``with_sharding_constraint`` calls, and
they degrade to plain layers when no mesh axis "mp" exists (serial ==
parallel numerics, the reference's key test invariant).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from . import fleet


# Trace-time mesh override (serving/distributed.py): the sharded serving
# engine traces its compiled step under a PER-ENGINE mesh — DP replicas
# each own a submesh, so the global fleet HCG cannot carry it.  Installed
# only around trace-triggering calls (Engine.warmup) on one thread;
# constrain() captures the NamedSharding into the jaxpr at trace time, so
# steady-state dispatches never read this.
_MESH_OVERRIDE = [None]


def _mesh():
    if _MESH_OVERRIDE[0] is not None:
        return _MESH_OVERRIDE[0]
    hcg = fleet.get_hybrid_communicate_group()
    return hcg.mesh if hcg is not None else None


def _mp_size() -> int:
    m = _mesh()
    return m.shape["mp"] if m is not None and "mp" in m.axis_names else 1


def constrain(x, *spec_entries):
    """with_sharding_constraint if a mesh is active, else identity."""
    m = _mesh()
    if m is None:
        return x
    spec = P(*spec_entries)
    return jax.lax.with_sharding_constraint(x, NamedSharding(m, spec))


def act_constrain(x, seq, feat):
    """Constrain a [batch..., seq, feature] activation.

    ``seq``/``feat`` are the mesh-axis entries for the sequence and feature
    dims.  Rank-2 inputs (a [tokens, feature] slice, e.g. inside a vmapped
    MoE expert) have no batch or sequence dim: the seq entry (which would
    otherwise be mis-applied to the token dim — sequence parallelism is
    meaningless there) is dropped and only the feature entry kept.
    """
    if x.ndim == 2:
        return constrain(x, None, feat)
    return constrain(x, ("dp", "sharding"), seq, feat)


def _seq_axes(sequence_parallel: bool):
    # Megatron-SP: outside the matmuls, activations are sharded on the
    # sequence dim over the SAME mp axis (reference:
    # sequence_parallel_utils.py); inside, on the hidden dim.
    return "mp" if sequence_parallel else None


class ColumnParallelLinear(Layer):
    """Weight (in, out) sharded on out ("column") over mp.

    gather_output=False leaves the activation sharded on the feature dim
    (feeding a RowParallelLinear), True gathers it (reference parity).
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 sequence_parallel=False, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.sequence_parallel = sequence_parallel
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            partition=P(None, "mp"))
        self.bias = self.create_parameter(
            (out_features,), is_bias=True, partition=P("mp")) if has_bias else None

    def forward(self, x):
        if self.sequence_parallel:
            # incoming activation is seq-sharded; XLA all-gathers it for the
            # matmul (the AllGatherOp in the reference)
            x = act_constrain(x, "mp", None)
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = act_constrain(y, None, None)
        else:
            y = act_constrain(y, None, "mp")
        return y


class RowParallelLinear(Layer):
    """Weight (in, out) sharded on in ("row") over mp; the contraction over
    the sharded dim makes XLA emit the all-reduce (or reduce-scatter when
    sequence_parallel leaves the output seq-sharded)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 sequence_parallel=False, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.sequence_parallel = sequence_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            partition=P("mp", None))
        self.bias = self.create_parameter(
            (out_features,), is_bias=True, partition=P()) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = act_constrain(x, None, "mp")
        y = F.linear(x, self.weight, None)
        if self.sequence_parallel:
            # ReduceScatterOp: output seq-sharded over mp
            y = act_constrain(y, "mp", None)
        else:
            y = act_constrain(y, None, None)
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over mp."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02) if weight_attr is None else None,
            partition=P("mp", None))

    def forward(self, ids):
        out = F.embedding(ids, self.weight)
        return constrain(out, ("dp", "sharding"), None, None)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross entropy.

    Reference: the CUDA kernel c_softmax_with_cross_entropy, which computes
    softmax over a vocab dim split across mp ranks with two allreduces
    (max, sumexp).  GSPMD derives the same two collectives from the logits'
    vocab sharding — we only keep the logits constrained and compute CE in
    fp32.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        logits = constrain(logits, ("dp", "sharding"), None, "mp")
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# Megatron-SP helper layers (reference: fleet/utils/sequence_parallel_utils.py)
# ---------------------------------------------------------------------------

class ColumnSequenceParallelLinear(ColumnParallelLinear):
    def __init__(self, *args, **kwargs):
        kwargs["sequence_parallel"] = True
        super().__init__(*args, **kwargs)


class RowSequenceParallelLinear(RowParallelLinear):
    def __init__(self, *args, **kwargs):
        kwargs["sequence_parallel"] = True
        super().__init__(*args, **kwargs)


def scatter_to_sequence_parallel(x):
    """ScatterOp: shard activation seq dim over mp (no data movement under
    GSPMD — just a resharding constraint)."""
    return constrain(x, ("dp", "sharding"), "mp", None)


def gather_from_sequence_parallel(x):
    """GatherOp: make the activation fully replicated on the seq dim."""
    return constrain(x, ("dp", "sharding"), None, None)


def mark_as_sequence_parallel_parameter(param):  # API parity; grads of SP
    return param  # params are already correct under GSPMD (global arrays)
