"""Pipeline parallelism, TPU-native (single-SPMD-program pipelining).

Reference surface (SURVEY.md §2.5): ``PipelineLayer`` built from
``LayerDesc``/``SharedLayerDesc`` with seg_method stage partitioning
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py),
``PipelineParallel.train_batch`` running 1F1B / interleaved schedules with
P2P send/recv per microbatch (meta_parallel/pipeline_parallel.py,
pp_utils/p2p_communication.py), and the static-graph fleet_executor.

TPU redesign — why this is NOT a port: the reference runs one Python process
per stage and hand-schedules P2P.  Under XLA/SPMD every device runs ONE
compiled program, so the pipeline is expressed as data movement inside that
program instead:

- the repeated (homogeneous) transformer body keeps its per-layer parameters
  STACKED along a leading layer axis that is sharded over the mesh's ``pp``
  axis → each pipeline stage physically holds only its ``L/pp`` layer slice
  (the memory win pipeline parallelism exists for);
- microbatches stream through a shift register of per-stage activations;
  the shift is a roll on the pp-sharded stage dim, which XLA lowers to an
  ICI collective-permute — exactly the reference's send/recv, but emitted by
  the compiler and overlapped by the latency-hiding scheduler;
- stage compute is ``vmap`` over the stage dim of an inner ``lax.scan`` over
  the per-stage layer slice, so the whole schedule (fill, steady state,
  drain) is one fused XLA loop — the GPipe schedule; backward runs through
  it by ``jax.grad`` with per-layer rematerialisation standing in for 1F1B's
  memory discipline (see schedule note below);
- the circular/interleaved schedule (reference "virtual pipeline stages")
  maps to ``num_virtual_pipeline_stages`` chunks per stage with the
  activation wrapping from the last stage back to stage 0.

Schedule note: classic 1F1B exists to bound live activations at
``O(pp · microbatch)`` instead of GPipe's ``O(num_micro · microbatch)``.
Here backward is compiler-scheduled, so the same bound is achieved by
rematerialising each layer (``use_recompute``) rather than by interleaving
explicit F/B ticks; the schedule knob is kept for API parity and selects the
storage layout (plain vs circular).  The bound is measured, not just
argued: tests/test_pipeline.py::TestRematMemoryBound compiles the pp=2 ×
8-microbatch llama with and without remat and asserts the XLA activation
highwater ratio (0.098 measured on the 8-device CPU mesh, 2026-07-30).
"""

from __future__ import annotations

import math
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import random as prandom
from ..nn.layer import Layer, ParamMeta
from . import fleet
from .mp_layers import _mesh, constrain as _constrain

_SEP = "__"  # flat-name separator for stacked parameter attributes


def _pp_size() -> int:
    m = _mesh()
    return m.shape["pp"] if m is not None and "pp" in m.axis_names else 1


# ---------------------------------------------------------------------------
# Layer descriptors (API parity with pp_layers.py)
# ---------------------------------------------------------------------------

class LayerDesc:
    """Lazy layer description: class + ctor args, built at partition time."""

    def __init__(self, layer_func, *inputs, **kwargs):
        if not (isinstance(layer_func, type) and issubclass(layer_func, Layer)):
            raise TypeError("LayerDesc expects a Layer subclass")
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared across pipeline positions
    (reference: tied input/output embeddings).  The first occurrence of a
    ``key`` owns the layer; later occurrences reuse the same instance, so
    the shared parameters appear once in the param pytree and gradients from
    every use site accumulate into them automatically (the reference needs
    an explicit allreduce between first and last stage for this).
    ``forward_func(layer, *args)`` customises how non-owner positions call
    the shared layer."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr
                 ="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedLayerProxy(Layer):
    """Calls a shared layer owned elsewhere without re-registering its
    parameters (the instance is stored outside the sublayer registry)."""

    def __init__(self, shared: Layer, forward_func=None):
        super().__init__()
        object.__setattr__(self, "_shared_ref", shared)
        object.__setattr__(self, "_forward_func", forward_func)

    def forward(self, *args, **kwargs):
        if self._forward_func is not None:
            return self._forward_func(self._shared_ref, *args, **kwargs)
        return self._shared_ref(*args, **kwargs)


# ---------------------------------------------------------------------------
# The stacked-parameter pipeline engine
# ---------------------------------------------------------------------------

class StackedPipelineStages(Layer):
    """A homogeneous run of ``n_layers`` identical-structure layers with
    parameters stacked on a leading layer axis (sharded over ``pp``).

    Serial semantics are identical to applying the layers in sequence; with
    ``num_stages > 1`` the forward executes the pipelined microbatch
    schedule described in the module docstring.

    ``extra_is_batched`` marks which of the forward's extra positional args
    carry a leading batch dim (they are microbatched and travel through the
    pipeline shift register alongside the activation); unmarked extras are
    closed over (broadcast to every stage).
    """

    def __init__(self, build_layer: Callable[[], Layer], n_layers: int,
                 num_stages: Optional[int] = None,
                 num_microbatches: Optional[int] = None,
                 num_virtual_pipeline_stages: int = 1,
                 use_recompute: bool = False, recompute_policy=None,
                 extra_is_batched: Sequence[bool] = (),
                 has_aux: bool = False):
        super().__init__()
        self.n_layers = n_layers
        # has_aux: template forward returns (x, aux_scalar); aux is summed
        # over layers (and averaged over microbatches in the pipelined
        # schedule, approximating the full-batch gate statistics) and
        # returned as (out, aux_total) — aux flows through outputs, never a
        # side channel, so it survives checkpoint/scan/vmap boundaries.
        self.has_aux = has_aux
        self.num_stages = num_stages if num_stages is not None else _pp_size()
        self.num_microbatches = num_microbatches
        self.num_chunks = num_virtual_pipeline_stages
        self.use_recompute = use_recompute
        self.recompute_policy = recompute_policy
        self.extra_is_batched = tuple(extra_is_batched)
        if n_layers % max(self.num_stages, 1):
            raise ValueError(
                f"n_layers={n_layers} not divisible by "
                f"num_stages={self.num_stages}")
        if self.num_chunks > 1 and n_layers % (self.num_stages * self.num_chunks):
            raise ValueError("n_layers must divide num_stages * "
                             "num_virtual_pipeline_stages")

        # Build each layer the same way a Python loop would (same RNG draw
        # order as the unstacked model → identical initial numerics), then
        # hoist their parameters into stacked arrays.  The template is NOT
        # registered as a sublayer: its per-instance params are superseded
        # by the stacked arrays; it remains only as the traced callee.
        instances = [build_layer() for _ in range(n_layers)]
        object.__setattr__(self, "template", instances[0])
        per_layer = [dict(inst.named_parameters()) for inst in instances]
        metas = instances[0].param_meta()
        self._param_names = list(per_layer[0].keys())

        # Storage order of the stacked layer axis.  With virtual-pipeline
        # chunks the runtime layout is stage-major ([S, C, Lps]) so that the
        # static pp sharding of the leading dim keeps every chunk slice
        # local to its stage (otherwise XLA would reshard all stacked params
        # every step).  perm[p] = original layer index stored at position p.
        S, C = max(self.num_stages, 1), self.num_chunks
        Lps = n_layers // (S * C)
        if S > 1 and C > 1:
            perm = [(c * S + s) * Lps + j
                    for s in range(S) for c in range(C) for j in range(Lps)]
        else:
            perm = list(range(n_layers))
        self._layer_perm = perm

        for name in self._param_names:
            vals = [per_layer[i][name] for i in perm]
            if isinstance(vals[0], jax.ShapeDtypeStruct):
                # meta_init() construction: stack abstractly
                stacked = jax.eval_shape(
                    lambda *xs: jnp.stack(xs, axis=0), *vals)
            else:
                stacked = jnp.stack(vals, axis=0)
            meta = metas.get(name, ParamMeta())
            base = meta.partition
            entries = (list(base) if base is not None else [])
            entries += [None] * (stacked.ndim - 1 - len(entries))
            part = P("pp", *entries) if self.num_stages > 1 else P(None, *entries)
            self._register_parameter(
                name.replace(".", _SEP), stacked,
                ParamMeta(trainable=meta.trainable, partition=part,
                          is_bias=meta.is_bias))

    # -- helpers -----------------------------------------------------------

    def _extra_mode_layers(self):
        # train()/eval() must reach the template even though it is outside
        # the sublayer registry (its params are superseded by the stack)
        return (self.template,)

    def stacked_params(self) -> Dict[str, jax.Array]:
        """Current (possibly traced/swapped) stacked arrays keyed by the
        template's flat param names."""
        return {n: getattr(self, n.replace(".", _SEP))
                for n in self._param_names}

    def _call_layer(self, params_i, key_i, x, static_extras, batched_extras,
                    flags):
        from ..nn.layer import _swapped_params

        def run(x, *bextras):
            args = _merge_extras(static_extras, bextras, flags)
            with _swapped_params(self.template, params_i), \
                    prandom.rng_scope(key_i):
                return self.template(x, *args)

        if self.use_recompute:
            run = jax.checkpoint(run, policy=self.recompute_policy)
        return run(x, *batched_extras)

    def _scan_layers(self, params, keys, x, static_extras, batched_extras,
                     flags):
        """Serially apply a [L, ...] slice of stacked layers via lax.scan.
        Returns (out, aux_sum) when has_aux else (out, None)."""
        if not self.has_aux:
            def body(carry, xs):
                p, k = xs
                return (self._call_layer(p, k, carry, static_extras,
                                         batched_extras, flags), None)
            out, _ = jax.lax.scan(body, x, (params, keys))
            return out, None

        def body(carry, xs):
            h, aux = carry
            p, k = xs
            h, a = self._call_layer(p, k, h, static_extras,
                                    batched_extras, flags)
            return (h, aux + a.astype(aux.dtype)), None
        (out, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), (params, keys))
        return out, aux

    # -- forward -----------------------------------------------------------

    def forward(self, x, *extras):
        params = self.stacked_params()
        # Per-layer RNG keys: a scanned body traces once, so ambient
        # next_key() would give every layer the same dropout mask; instead
        # derive one key per stored layer position from its ORIGINAL layer
        # index (so storage permutation doesn't change masks).  The
        # pipelined path additionally folds in the tick index so each
        # microbatch draws independent masks.
        base_key = (prandom.next_key("stacked_layers")
                    if prandom.in_rng_scope() else jax.random.key(0))
        keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
            jnp.asarray(self._layer_perm, jnp.int32))
        # Extras marked batched are demoted to static when their leading dim
        # is not the batch (e.g. a broadcast [1,1,S,S] attention mask).
        flags = tuple(self.extra_is_batched) + (False,) * (
            len(extras) - len(self.extra_is_batched))
        flags = tuple(
            f and e is not None and getattr(e, "ndim", 0) > 0
            and e.shape[0] == x.shape[0] for f, e in zip(flags, extras))
        static_extras, batched_extras = _split_extras(extras, flags)
        if self.num_stages <= 1:
            out, aux = self._scan_layers(params, keys, x, static_extras,
                                         batched_extras, flags)
        else:
            out, aux = self._pipelined(params, keys, x, static_extras,
                                       batched_extras, flags)
        return (out, aux) if self.has_aux else out

    # -- the pipelined schedule -------------------------------------------

    def _pipelined(self, params, keys, x, static_extras, batched_extras,
                   flags):
        S, C = self.num_stages, self.num_chunks
        B = x.shape[0]
        M = self.num_microbatches or S
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        mb = B // M
        Lps = self.n_layers // (S * C)  # layers per stage per chunk

        # Storage is stage-major ([S, C, Lps]; see __init__): chunk c of
        # stage s holds original layers [(c*S + s)*Lps, ...), the
        # reference's interleaved "virtual pipeline stage" layout.  Slicing
        # chunk c (dim 1) is local — the pp-sharded leading dim is intact.
        def to_sc(t):
            return t.reshape((S, C, Lps) + t.shape[1:])
        sp = {k: _constrain(to_sc(v), "pp") for k, v in params.items()}
        ksc = to_sc(keys)

        # microbatch the activation + batched extras: [M, mb, ...]
        def to_micro(t):
            return t.reshape((M, mb) + t.shape[1:])
        x_m = to_micro(x)
        bex_m = tuple(to_micro(e) for e in batched_extras)

        def stage_fn(stage_params, stage_keys, h, bextras):
            out, aux = self._scan_layers(stage_params, stage_keys, h,
                                         static_extras, bextras, flags)
            if aux is None:
                aux = jnp.zeros((), jnp.float32)
            return out, aux

        vstage = jax.vmap(stage_fn)  # over the stage dim

        def shift(new_head, buf):
            # roll the stage dim by one: stage s receives stage s-1's
            # output; on the pp-sharded dim XLA lowers this slice+concat to
            # an ICI collective-permute (the reference's p2p send/recv).
            rolled = jnp.concatenate([new_head[None], buf[:-1]], axis=0)
            return _constrain(rolled, "pp")

        def _fill(shape, dtype):
            # fill/drain ticks carry dummy data; boolean buffers (attention
            # masks) must be all-True so softmax rows aren't fully masked —
            # 0*NaN in the discarded ticks' cotangents would poison grads
            return (jnp.ones(shape, dtype) if dtype == jnp.bool_
                    else jnp.zeros(shape, dtype))

        s_idx = jnp.arange(S)

        def one_pass(x_m, bex_m, chunk, tick0):
            """GPipe shift-register over the stage ring for one chunk:
            T = M + S - 1 ticks (fill, steady state, drain)."""
            stage_p = {k: v[:, chunk] for k, v in sp.items()}
            stage_k = ksc[:, chunk]
            state = _fill((S,) + x_m.shape[1:], x.dtype)
            bstate = tuple(_fill((S,) + e.shape[1:], e.dtype) for e in bex_m)
            aux0 = jnp.zeros((), jnp.float32)
            T = M + S - 1

            def tick(carry, t):
                state, bstate, aux = carry
                idx = jnp.minimum(t, M - 1)
                new_state = shift(x_m[idx], state)
                new_bstate = tuple(shift(e[idx], b)
                                   for e, b in zip(bex_m, bstate))
                # fold the global tick into the stage keys: every microbatch
                # draws independent dropout masks
                k_t = jax.vmap(jax.vmap(
                    lambda k: jax.random.fold_in(k, tick0 + t)))(stage_k)
                out, aux_s = vstage(stage_p, k_t, new_state, new_bstate)
                out = _constrain(out, "pp")
                if self.has_aux:
                    # count only live stages (fill/drain slots hold dummies)
                    live = (t >= s_idx) & (t - s_idx < M)
                    aux = aux + jnp.sum(jnp.where(live, aux_s, 0.0))
                return (out, new_bstate, aux), out[-1]

            (_, _, aux), ys = jax.lax.scan(tick, (state, bstate, aux0),
                                           jnp.arange(T))
            return ys[T - M:], aux  # [M, mb, ...] in microbatch order

        # C passes over the ring; each microbatch traverses all L layers in
        # order.  (Classic interleaving merges the drains/fills of adjacent
        # chunks; the extra (C-1)*(S-1) bubble ticks here are the price of a
        # single fused scan per chunk — revisit if profiles show it.)
        aux_total = jnp.zeros((), jnp.float32)
        for c in range(C):
            x_m, aux_c = one_pass(x_m, bex_m, c, c * (M + S - 1))
            aux_total = aux_total + aux_c
        # per-microbatch gate statistics averaged to the full-batch scale
        return (x_m.reshape((B,) + x_m.shape[2:]),
                aux_total / M if self.has_aux else None)


def _split_extras(extras, flags):
    """Split by the (already normalised, per-position) flags; merge puts
    every extra back in its exact original position."""
    static = tuple(e for e, f in zip(extras, flags) if not f)
    batched = tuple(e for e, f in zip(extras, flags) if f)
    return static, batched


def _merge_extras(static_extras, batched_extras, flags):
    out, si, bi = [], 0, 0
    for f in flags:
        if f:
            out.append(batched_extras[bi]); bi += 1
        else:
            out.append(static_extras[si]); si += 1
    return tuple(out)


# ---------------------------------------------------------------------------
# PipelineLayer (paddle API parity)
# ---------------------------------------------------------------------------

class PipelineLayer(Layer):
    """``paddle.distributed.fleet.meta_parallel.PipelineLayer`` parity.

    Accepts a flat list of layers / ``LayerDesc``s.  The longest homogeneous
    run of identical LayerDescs becomes the pipelined body (stacked params,
    pp-sharded); layers before/after it run replicated over pp (embedding /
    head — cheap relative to the body, and keeping them replicated avoids
    the reference's tied-weight allreduce).  ``seg_method`` is honoured for
    its "uniform" meaning; "layer:ClassName" selects which class forms the
    body explicitly.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=1,
                 num_microbatches=None):
        super().__init__()
        self.loss_fn = loss_fn
        num_stages = num_stages or _pp_size()
        self.num_stages = num_stages

        descs = list(layers)
        body_cls = None
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            body_cls = seg_method.split(":", 1)[1]
        lo, hi = _homogeneous_run(descs, body_cls)
        if num_stages > 1 and (hi - lo) % num_stages:
            raise ValueError(
                f"pipeline body has {hi - lo} layers, not divisible by "
                f"num_stages={num_stages}")

        self._shared = {}
        from ..nn.layers_common import LayerList
        self.pre = LayerList([self._build(d) for d in descs[:lo]])
        body = descs[lo:hi]
        if body:
            # _homogeneous_run only selects LayerDesc runs, so body[0] is
            # always a desc whose build_layer makes fresh instances
            self.body = StackedPipelineStages(
                body[0].build_layer,
                n_layers=len(body), num_stages=num_stages,
                num_microbatches=num_microbatches,
                num_virtual_pipeline_stages=num_virtual_pipeline_stages,
                use_recompute=recompute_interval > 0)
        else:
            self.body = None
            if num_stages > 1:
                warnings.warn("no homogeneous layer run found; executing "
                              "serially with pp-replicated parameters")
        self.post = LayerList([self._build(d) for d in descs[hi:]])

    def _build(self, desc):
        if isinstance(desc, SharedLayerDesc):
            if desc.layer_name in self._shared:
                return _SharedLayerProxy(self._shared[desc.layer_name],
                                         desc.forward_func)
            layer = desc.build_layer()
            self._shared[desc.layer_name] = layer
            return layer
        if isinstance(desc, LayerDesc):
            return desc.build_layer()
        return desc

    def forward(self, x, *extras):
        for l in self.pre:
            x = l(x)
        if self.body is not None:
            x = self.body(x, *extras)
        for l in self.post:
            x = l(x)
        return x


def _homogeneous_run(descs, body_cls: Optional[str]) -> Tuple[int, int]:
    """Find [lo, hi) of the longest run of LayerDescs with the same class
    (or the run of class named ``body_cls``)."""
    def cls_of(d):
        if isinstance(d, LayerDesc) and not isinstance(d, SharedLayerDesc):
            return d.layer_func
        return None
    best = (0, 0)
    i = 0
    while i < len(descs):
        c = cls_of(descs[i])
        j = i
        while j < len(descs) and cls_of(descs[j]) is c and c is not None:
            j += 1
        if c is not None:
            if body_cls is not None:
                if c.__name__ == body_cls:
                    return (i, j)
            elif j - i > best[1] - best[0]:
                best = (i, j)
        i = max(j, i + 1)
    return best


# ---------------------------------------------------------------------------
# PipelineParallel wrapper (meta_parallel parity)
# ---------------------------------------------------------------------------

class PipelineParallel(Layer):
    """Reference: meta_parallel/pipeline_parallel.py — wraps a PipelineLayer
    and exposes ``train_batch``.  Here train_batch builds (once) a compiled
    TrainStep over the fleet mesh and runs one step; the microbatch schedule
    lives inside the compiled program, not in Python."""

    def __init__(self, model: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        self.model = model
        self._hcg = hcg or fleet.get_hybrid_communicate_group()
        self._strategy = strategy
        self._step = None
        self._state = None

    def forward(self, *args, **kwargs):
        return self.model(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        if lr_scheduler is not None:
            optimizer._learning_rate = lr_scheduler
        if self._step is not None and (
                self._step.optimizer is not optimizer
                or self._step.scaler is not scaler):
            self._step = None  # optimizer/scaler swapped: rebuild the step
        from ..jit import TrainStep
        if self._step is None:
            loss_fn = self.model.loss_fn or (
                lambda model, batch: model(*batch).mean())

            def step_loss(model, batch):
                return loss_fn(model, batch)
            self._step = TrainStep(
                self.model, step_loss, optimizer, scaler=scaler,
                mesh=self._hcg.mesh if self._hcg else None)
            self._state = self._step.init_state()
        self._state, metrics = self._step(self._state, data)
        return metrics["loss"]
