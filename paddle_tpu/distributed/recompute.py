"""Activation recomputation (``fleet.utils.recompute`` parity).

Reference: python/paddle/distributed/fleet/recompute/recompute.py — a
PyLayer that stashes RNG state, drops activations, and re-runs forward
during backward.  TPU-native: ``jax.checkpoint`` (remat) does exactly this
inside the compiled step, with selectable policies controlling what XLA may
keep (the knob the reference lacks).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..nn.layer import Layer

POLICIES = {
    "none": None,  # save nothing extra: recompute everything
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def recompute(function: Callable, *args, use_reentrant=True, policy=None,
              preserve_rng_state=True, **kwargs):
    """Run ``function`` under rematerialisation.

    RNG state is preserved by construction: dropout keys are derived
    deterministically from the step key (core.random), so the recomputed
    forward draws identical masks — the property the reference implements
    with CUDA RNG state stashing.
    """
    pol = POLICIES.get(policy, policy) if isinstance(policy, str) else policy
    fn = jax.checkpoint(function, policy=pol)
    return fn(*args, **kwargs)


class RecomputeWrapper(Layer):
    """Wrap a sublayer so its forward runs under remat inside compiled steps."""

    def __init__(self, inner: Layer, policy: Optional[str] = None):
        super().__init__()
        self.inner = inner
        self._policy = POLICIES.get(policy, policy) if isinstance(policy, str) else policy

    def forward(self, *args, **kwargs):
        fn = jax.checkpoint(lambda *a: self.inner(*a, **kwargs),
                            policy=self._policy)
        return fn(*args)
