"""Fleet: hybrid-parallel orchestration (``paddle.distributed.fleet`` parity).

Reference: python/paddle/distributed/fleet/fleet.py (Fleet.init),
base/distributed_strategy.py (DistributedStrategy),
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py.

TPU redesign: ``fleet.init`` builds one global ``HybridCommunicateGroup``
holding a jax Mesh; ``distributed_model`` is mostly a no-op (parallelism is
expressed by parameter partition specs + the TrainStep compiler) but keeps
the reference's call shape so training scripts port 1:1;
``distributed_optimizer`` wires mesh-aware grad clipping (the TP/sharding-
aware global-norm behaviour HybridParallelOptimizer implements).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

import jax

from ...nn.clip import ClipGradByGlobalNorm
from ..topology import AXIS_ORDER, HybridCommunicateGroup, HybridTopology
from . import utils  # noqa: F401 — fleet.utils.recompute &c. (reference path)
from . import elastic  # noqa: F401 — fleet.elastic (reference path)

_HYBRID_PARALLEL_GROUP: Optional[HybridCommunicateGroup] = None


@dataclasses.dataclass
class DistributedStrategy:
    """Serializable strategy bag (reference: protobuf-backed
    DistributedStrategy; here a dataclass with json round-trip)."""

    hybrid_configs: Dict[str, int] = dataclasses.field(default_factory=dict)
    amp: bool = False
    amp_configs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    recompute: bool = False
    recompute_configs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    sharding: bool = False
    sharding_configs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    pipeline_configs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "DistributedStrategy":
        return cls(**json.loads(s))


_PS_RUNTIME = None  # non-collective (parameter-server) mode state


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None,
         devices=None):
    """Build the global topology/mesh (reference: Fleet.init → topology 3.2).

    No rendezvous/NCCL init is needed; multi-host process bootstrap is
    ``paddle_tpu.distributed.init_parallel_env`` →
    ``jax.distributed.initialize``.

    Passing a ``ps.PaddleCloudRoleMaker`` (or ``is_collective=False``)
    selects parameter-server mode (reference: fleet.init(role) →
    init_server/run_server/init_worker flow, SURVEY §2.5); the returned
    object is then a ``ps.PsRuntime`` configured later via
    ``fleet.set_ps_tables(configs)``.
    """
    global _HYBRID_PARALLEL_GROUP, _PS_RUNTIME
    from ..ps import PaddleCloudRoleMaker, PsRuntime
    # PS mode: any role-maker object (PaddleCloudRoleMaker OR
    # UserDefinedRoleMaker — duck-typed on is_server/is_worker) with
    # is_collective=False, or env-discovered when none is given
    is_role_obj = role_maker is not None and \
        callable(getattr(role_maker, "is_server", None))
    if (is_role_obj and not getattr(role_maker, "is_collective", False)) \
            or (role_maker is None and not is_collective):
        role = role_maker or PaddleCloudRoleMaker()
        _PS_RUNTIME = PsRuntime(role, configs=[])
        return _PS_RUNTIME
    strategy = strategy or DistributedStrategy()
    topo = HybridTopology.from_hybrid_configs(strategy.hybrid_configs)
    n = len(devices) if devices is not None else jax.device_count()
    topo.infer_missing(n)
    if topo.world_size == 1 and n > 1 and not strategy.hybrid_configs:
        topo.dp_degree = n  # pure-DP default, like init_parallel_env
    mesh = topo.build_mesh(devices)
    _HYBRID_PARALLEL_GROUP = HybridCommunicateGroup(topo, mesh)
    _HYBRID_PARALLEL_GROUP.strategy = strategy
    return _HYBRID_PARALLEL_GROUP


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HYBRID_PARALLEL_GROUP


def set_ps_tables(configs, master_endpoint=None):
    """Declare the PS tables (reference: table config in the strategy
    proto). Must run before init_server/init_worker."""
    if _PS_RUNTIME is None:
        raise RuntimeError("fleet.init(role_maker, is_collective=False) first")
    _PS_RUNTIME.configs = list(configs)
    if master_endpoint:
        _PS_RUNTIME.master_endpoint = master_endpoint
    return _PS_RUNTIME


def _ps() :
    if _PS_RUNTIME is None:
        raise RuntimeError("not in parameter-server mode")
    return _PS_RUNTIME


def is_server() -> bool:
    return _PS_RUNTIME is not None and _PS_RUNTIME.role.is_server()


def is_worker() -> bool:
    return _PS_RUNTIME is not None and _PS_RUNTIME.role.is_worker()


def init_server():
    _ps().init_server()


def run_server():
    _ps().run_server()


def init_worker():
    _ps().init_worker()


def stop_worker():
    _ps().stop_worker()


def _reset():  # test helper
    global _HYBRID_PARALLEL_GROUP, _PS_RUNTIME
    _HYBRID_PARALLEL_GROUP = None
    _PS_RUNTIME = None
    from .. import communication as _comm
    _comm._parallel_env_initialized = False


def distributed_model(model):
    """Reference: fleet.distributed_model wraps the model per active axes
    (TensorParallel/PipelineParallel/...).  Here sharding is declared on the
    parameters themselves, so this validates and returns the model."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init(...) first")
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Make the optimizer hybrid-parallel aware (reference:
    HybridParallelOptimizer): a ClipGradByGlobalNorm is upgraded to psum its
    squared-norms over every mesh axis that partitions gradients, so the
    global norm matches the serial run exactly."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init(...) first")
    # Under GSPMD/jit, gradients are global arrays: jnp.sum over a sharded
    # array already yields the global sum, so ClipGradByGlobalNorm is correct
    # as-is.  Explicit psum axes are only needed inside shard_map regions
    # (the pipeline body sets them itself).  Nothing to rewrite here — just
    # attach the hcg so the optimizer can consult the topology.
    optimizer._hcg = hcg
    return optimizer


# ---------------------------------------------------------------------------
# role/topology introspection (reference: fleet/base/role_maker.py surface
# re-exported on the fleet object — worker/server counts and endpoints)
# ---------------------------------------------------------------------------

def _role_env():
    import os as _os
    return _os.environ


def worker_index() -> int:
    """Reference: fleet.worker_index — this trainer's rank."""
    if _PS_RUNTIME is not None:
        return _PS_RUNTIME.role.trainer_id
    from ..communication import get_rank
    return get_rank()


def worker_num() -> int:
    if _PS_RUNTIME is not None:
        return _PS_RUNTIME.role.trainer_num
    from ..communication import get_world_size
    return get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0


def worker_endpoints(to_string: bool = False):
    eps = [p for p in _role_env().get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if p]
    return ",".join(eps) if to_string else eps


def server_num() -> int:
    return len(server_endpoints())


def server_index() -> int:
    if _PS_RUNTIME is not None:
        return _PS_RUNTIME.role.server_id
    return -1


def server_endpoints(to_string: bool = False):
    if _PS_RUNTIME is not None:
        eps = _PS_RUNTIME.role.server_endpoints
    else:
        eps = [p for p in _role_env().get("PADDLE_PSERVERS_IP_PORT_LIST",
                                          "").split(",") if p]
    return ",".join(eps) if to_string else eps


def barrier_worker():
    """Reference: fleet.barrier_worker — block until every trainer
    arrives (maps onto the collective barrier; no-op at world 1)."""
    from ..communication import barrier, is_initialized
    if is_initialized() or worker_num() > 1:
        barrier()


class UserDefinedRoleMaker:
    """Reference: fleet.UserDefinedRoleMaker — explicit role assignment
    instead of env discovery.  Implements the full role interface
    PsRuntime consumes (same protocol as ps.PaddleCloudRoleMaker)."""

    def __init__(self, is_collective=False, current_id=0,
                 role="worker", worker_num=1, server_endpoints=None,
                 **kw):
        self.is_collective = is_collective
        self.trainer_id = int(current_id)
        self.trainer_num = int(worker_num)
        self._role = role.lower()
        self.server_endpoints = list(server_endpoints or [])
        self.server_id = int(current_id) if self._role == "server" else -1

    def is_server(self) -> bool:
        return self._role == "server"

    def is_worker(self) -> bool:
        return self._role == "worker"

    def worker_index(self) -> int:
        return self.trainer_id

    def worker_num(self) -> int:  # noqa: F811 — mirrors the role protocol
        return self.trainer_num

    def server_num(self) -> int:
        # `or 1` floor matches PaddleCloudRoleMaker: an endpoint-less
        # config still describes a 1-server world (PsClient needs >= 1)
        return len(self.server_endpoints) or 1


class UtilBase:
    """Reference: fleet.UtilBase — cross-worker small-data utilities."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as _np

        import jax.numpy as _jnp

        from ..communication import ReduceOp, all_reduce
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}.get(str(mode).lower())
        if op is None:
            raise ValueError(f"UtilBase.all_reduce: mode {mode!r} not in "
                             "sum/max/min")
        out = all_reduce(_jnp.asarray(input), op=op)
        return _np.asarray(out)

    def barrier(self, comm_world="worker"):
        barrier_worker()

    def all_gather(self, input, comm_world="worker"):
        from ..misc import all_gather_object
        out = []
        all_gather_object(out, input)
        return out


util = UtilBase()

# reference exports the role makers on the fleet namespace too
from ..ps import PaddleCloudRoleMaker  # noqa: E402,F401


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
