"""``paddle.distributed.fleet.utils`` parity surface.

Reference: python/paddle/distributed/fleet/utils/ — recompute (activation
checkpointing), sequence_parallel_utils (Megatron-SP ops). Both are
implemented in their first-class homes here and re-exported at the
reference path so training scripts port unchanged.
"""

from ..recompute import RecomputeWrapper, recompute  # noqa: F401
from ..mp_layers import (  # noqa: F401
    ColumnSequenceParallelLinear, RowSequenceParallelLinear,
    gather_from_sequence_parallel, mark_as_sequence_parallel_parameter,
    scatter_to_sequence_parallel)
