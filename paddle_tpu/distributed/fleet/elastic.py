"""``paddle.distributed.fleet.elastic`` namespace parity.

Reference: python/paddle/distributed/fleet/elastic/manager.py (etcd
membership, scale events, relaunch) — SURVEY §2.7/§5.3. The TPU-native
implementation lives in ``paddle_tpu.launch.elastic`` (store-based
heartbeats, restart-based elasticity, preemption guard); this module is
the reference import path.
"""

from ...launch.elastic import ElasticManager  # noqa: F401

__all__ = ["ElasticManager"]
