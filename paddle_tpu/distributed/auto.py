"""Auto-parallel API (``paddle.distributed.shard_tensor`` parity).

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor,
Shard/Replicate/Partial placements) over C++ DistTensor + reshard functions
(paddle/phi/core/distributed/auto_parallel/).

TPU redesign: a "DistTensor" IS a jax global Array with a NamedSharding —
jax's sharding propagation plays the role of the reference's per-op SPMD
rules, and ``reshard`` is ``jax.device_put`` to a new sharding (XLA emits
the collective resharding program).  So this module is thin sugar mapping
paddle placements onto PartitionSpecs.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import fleet


class Placement:
    pass


class Shard(Placement):
    """Shard along tensor dim ``dim`` over the corresponding mesh axis."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending-reduction placement.  jax has no user-visible partial arrays
    outside shard_map; shard_tensor treats it as Replicate (the reduction
    happens where the value is produced)."""

    def __repr__(self):
        return "Partial()"


class DistAttr:
    def __init__(self, mesh, placements):
        self.mesh = mesh
        self.placements = placements


class ProcessMesh:
    """paddle.distributed.ProcessMesh parity: an N-d mesh with named dims."""

    def __init__(self, mesh: Sequence, dim_names: Optional[Sequence[str]] = None):
        import numpy as np
        arr = np.asarray(mesh)
        self.dim_names = list(dim_names or [f"d{i}" for i in range(arr.ndim)])
        devs = np.asarray(jax.devices(), dtype=object)[arr]
        self.jax_mesh = Mesh(devs, tuple(self.dim_names))

    @property
    def shape(self):
        return tuple(self.jax_mesh.devices.shape)


def _to_jax_mesh(mesh) -> Mesh:
    if isinstance(mesh, Mesh):
        return mesh
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    if mesh is None:
        hcg = fleet.get_hybrid_communicate_group()
        if hcg is None:
            raise RuntimeError("no mesh: pass one or call fleet.init")
        return hcg.mesh
    raise TypeError(f"unsupported mesh type {type(mesh)}")


def _placements_to_spec(mesh: Mesh, placements: Sequence[Placement],
                        ndim: int) -> P:
    entries: List = [None] * ndim
    for axis_name, placement in zip(mesh.axis_names, placements):
        if isinstance(placement, Shard):
            if entries[placement.dim] is None:
                entries[placement.dim] = axis_name
            elif isinstance(entries[placement.dim], tuple):
                entries[placement.dim] = entries[placement.dim] + (axis_name,)
            else:
                entries[placement.dim] = (entries[placement.dim], axis_name)
        # Replicate/Partial: nothing
    return P(*entries)


def shard_tensor(x, mesh=None, placements: Sequence[Placement] = (),
                 dist_attr=None, stop_gradient=None):
    """Place ``x`` on the mesh with the given per-mesh-dim placements."""
    if dist_attr is not None:
        mesh, placements = dist_attr.mesh, dist_attr.placements
    jmesh = _to_jax_mesh(mesh)
    spec = _placements_to_spec(jmesh, placements, jax.numpy.ndim(x))
    return jax.device_put(x, NamedSharding(jmesh, spec))


def reshard(x, mesh=None, placements: Sequence[Placement] = ()):
    """Change an array's distribution (reference: reshard pass inserting
    collectives; here XLA derives them from device_put)."""
    return shard_tensor(x, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_layer(layer, mesh=None, shard_fn=None):
    """Apply a per-parameter shard_fn(name, param) -> placements, or leave
    parameters replicated on the mesh."""
    jmesh = _to_jax_mesh(mesh)
    for name, p in list(layer.named_parameters()):
        placements = shard_fn(name, p) if shard_fn else [Replicate()]
        layer._assign_by_path(name, shard_tensor(p, jmesh, placements))
    return layer
