"""Auto-parallel API (``paddle.distributed.shard_tensor`` parity).

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor,
Shard/Replicate/Partial placements) over C++ DistTensor + reshard functions
(paddle/phi/core/distributed/auto_parallel/).

TPU redesign: a "DistTensor" IS a jax global Array with a NamedSharding —
jax's sharding propagation plays the role of the reference's per-op SPMD
rules, and ``reshard`` is ``jax.device_put`` to a new sharding (XLA emits
the collective resharding program).  So this module is thin sugar mapping
paddle placements onto PartitionSpecs.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import fleet


class Placement:
    pass


class Shard(Placement):
    """Shard along tensor dim ``dim`` over the corresponding mesh axis."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending-reduction placement (reference: Partial(reduce_type) in
    auto_parallel/placement_type.py).

    jax global Arrays cannot carry a pending reduction, so ``shard_tensor``
    with a Partial placement returns a :class:`PartialTensor` — an explicit
    pending-reduction value whose per-rank shards sum (or mean/max/min) to
    the global.  ``reshard`` materializes it with the reduction; any other
    use raises loudly instead of silently reading partial values (the
    round-1 behavior of treating Partial as Replicate was a silent
    semantic downgrade)."""

    def __init__(self, reduce_type: str = "sum"):
        if reduce_type not in ("sum", "avg", "mean", "max", "min"):
            raise ValueError(f"unsupported Partial reduce_type {reduce_type}")
        self.reduce_type = "mean" if reduce_type == "avg" else reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type!r})"


class DistAttr:
    def __init__(self, mesh, placements):
        self.mesh = mesh
        self.placements = placements


class ProcessMesh:
    """paddle.distributed.ProcessMesh parity: an N-d mesh with named dims."""

    def __init__(self, mesh: Sequence, dim_names: Optional[Sequence[str]] = None):
        import numpy as np
        arr = np.asarray(mesh)
        self.dim_names = list(dim_names or [f"d{i}" for i in range(arr.ndim)])
        devs = np.asarray(jax.devices(), dtype=object)[arr]
        self.jax_mesh = Mesh(devs, tuple(self.dim_names))

    @property
    def shape(self):
        return tuple(self.jax_mesh.devices.shape)


def _to_jax_mesh(mesh) -> Mesh:
    if isinstance(mesh, Mesh):
        return mesh
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    if mesh is None:
        hcg = fleet.get_hybrid_communicate_group()
        if hcg is None:
            raise RuntimeError("no mesh: pass one or call fleet.init")
        return hcg.mesh
    raise TypeError(f"unsupported mesh type {type(mesh)}")


def _placements_to_spec(mesh: Mesh, placements: Sequence[Placement],
                        ndim: int) -> P:
    entries: List = [None] * ndim
    for axis_name, placement in zip(mesh.axis_names, placements):
        if isinstance(placement, Shard):
            if entries[placement.dim] is None:
                entries[placement.dim] = axis_name
            elif isinstance(entries[placement.dim], tuple):
                entries[placement.dim] = entries[placement.dim] + (axis_name,)
            else:
                entries[placement.dim] = (entries[placement.dim], axis_name)
        # Replicate/Partial: nothing
    return P(*entries)


class PartialTensor:
    """Explicit pending-reduction value (the reference's DistTensor with a
    Partial placement).

    Internally a stacked global array of shape ``(axis_size, *shape)``
    sharded over the partial mesh axis on dim 0, so each rank owns one
    addend.  ``reshard`` to Replicate/Shard applies the reduction (XLA
    lowers the sum over the sharded dim to an all-reduce); any arithmetic
    or export raises, because reading partial values is the bug the
    reference's placement system exists to prevent."""

    def __init__(self, stacked, mesh: Mesh, axes: Sequence[str],
                 placements: Sequence[Placement], reduce_type: str):
        self._stacked = stacked          # (prod(axis sizes), *shape)
        self.mesh = mesh
        self.axes = tuple(axes)          # mesh axes the value is partial over
        self.placements = list(placements)
        self.reduce_type = reduce_type

    @property
    def shape(self):
        return self._stacked.shape[1:]

    @property
    def dtype(self):
        return self._stacked.dtype

    def __repr__(self):
        return (f"PartialTensor(shape={tuple(self.shape)}, "
                f"axes={self.axes}, reduce={self.reduce_type!r})")

    def _reduce(self):
        import jax.numpy as jnp
        red = {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max,
               "min": jnp.min}[self.reduce_type]
        return red(self._stacked, axis=0)

    def _refuse(self, what):
        raise RuntimeError(
            f"PartialTensor is a pending reduction over mesh axes "
            f"{self.axes}; {what} would read partial values. "
            "reshard(x, mesh, [Replicate()/Shard(d), ...]) first.")

    def __array__(self, *a, **k):
        self._refuse("converting to an array")

    def __jax_array__(self):
        self._refuse("using it in an op")

    def _refuse_op(self, *a, **k):
        self._refuse("arithmetic")

    __add__ = __radd__ = __sub__ = __rsub__ = _refuse_op
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _refuse_op
    __matmul__ = __rmatmul__ = __neg__ = _refuse_op


def shard_tensor(x, mesh=None, placements: Sequence[Placement] = (),
                 dist_attr=None, stop_gradient=None):
    """Place ``x`` on the mesh with the given per-mesh-dim placements.

    With one or more ``Partial`` placements the result is a
    :class:`PartialTensor` whose per-rank addends reduce to ``x`` (rank 0
    holds ``x``, the rest the reduction identity — the reference's
    init-on-rank-0 convention)."""
    if dist_attr is not None:
        mesh, placements = dist_attr.mesh, dist_attr.placements
    jmesh = _to_jax_mesh(mesh)
    partial_axes = [ax for ax, pl in zip(jmesh.axis_names, placements)
                    if isinstance(pl, Partial)]
    if partial_axes:
        return _make_partial(x, jmesh, partial_axes, placements)
    spec = _placements_to_spec(jmesh, placements, jax.numpy.ndim(x))
    _check_divisible(x, jmesh, spec)
    return jax.device_put(x, NamedSharding(jmesh, spec))


def _check_divisible(x, jmesh: Mesh, spec: P):
    """XLA shards evenly: every Shard-ed dim must divide by the axis size.
    The reference's reshard supports ragged tails; here that would need
    silent padding that changes the global shape — raise with the fix
    instead."""
    import numpy as np
    shape = jax.numpy.shape(x)
    for d, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = int(np.prod([jmesh.shape[a] for a in axes]))
        if shape[d] % n:
            raise ValueError(
                f"cannot Shard dim {d} (size {shape[d]}) over mesh axes "
                f"{axes} (total {n}): XLA requires even tiles. Pad the dim "
                f"to a multiple of {n} (e.g. paddle_tpu.concat with a pad "
                "block) or shard a divisible dim.")


def _make_partial(x, jmesh: Mesh, axes: Sequence[str],
                  placements: Sequence[Placement]) -> PartialTensor:
    import jax.numpy as jnp
    import numpy as np
    reduce_types = {pl.reduce_type for pl in placements
                    if isinstance(pl, Partial)}
    if len(reduce_types) > 1:
        raise ValueError(f"mixed Partial reduce types {reduce_types}")
    reduce_type = reduce_types.pop()
    n = int(np.prod([jmesh.shape[a] for a in axes]))
    x = jnp.asarray(x)
    if reduce_type in ("sum",):
        identity = jnp.zeros_like(x)
    elif reduce_type == "mean":
        identity = x  # mean of n copies of x is x
    else:  # max/min: identity = x itself keeps the reduction exact
        identity = x
    stacked = jnp.stack([x] + [identity] * (n - 1))
    # shard the stack dim over the partial axes; remaining placements
    # (Shard/Replicate on other mesh axes) apply to the value dims, shifted
    # by the stacking dim
    shifted = [Shard(pl.dim + 1) if isinstance(pl, Shard) else Replicate()
               for pl in placements]
    entries: List = list(_placements_to_spec(jmesh, shifted, x.ndim + 1))
    entries[0] = tuple(axes) if len(axes) > 1 else axes[0]
    spec = P(*entries)
    _check_divisible(stacked, jmesh, spec)
    stacked = jax.device_put(stacked, NamedSharding(jmesh, spec))
    return PartialTensor(stacked, jmesh, axes, placements, reduce_type)


def reshard(x, mesh=None, placements: Sequence[Placement] = ()):
    """Change an array's distribution (reference: reshard pass inserting
    collectives; here XLA derives them from device_put).  Resharding a
    :class:`PartialTensor` to Replicate/Shard materializes the pending
    reduction (all-reduce over the partial axes)."""
    if isinstance(x, PartialTensor):
        if any(isinstance(pl, Partial) for pl in placements):
            raise RuntimeError(
                "reshard of a PartialTensor to a Partial placement is a "
                "no-op request; target Replicate()/Shard(d) to reduce")
        x = x._reduce()
    return shard_tensor(x, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


class ShardDataloader:
    """Wrap a DataLoader so every batch lands on the mesh sharded along the
    batch dim (reference: paddle.distributed.shard_dataloader,
    auto_parallel/api.py).

    ``shard_dims`` names the mesh axis (or axes) carrying data parallelism;
    by default the mesh's first axis.  Batches may be arrays, sequences, or
    dicts — every array leaf is placed with Shard(0) over those axes.  With
    ``input_keys`` only the named dict entries are sharded (the rest are
    replicated)."""

    def __init__(self, dataloader, meshes=None, input_keys=None,
                 shard_dims=None, is_dataset_splitted=False):
        self._dl = dataloader
        if isinstance(meshes, (list, tuple)):
            if len({id(m) for m in meshes}) > 1:
                # reference: per-pipeline-stage meshes (inputs on the first
                # stage, labels on the last); a single-SPMD program has one
                # mesh, so silently using meshes[0] would misplace data
                raise NotImplementedError(
                    "per-stage mesh lists are not supported: the pipeline "
                    "is one SPMD program over one mesh — pass that mesh")
            meshes = meshes[0] if meshes else None
        self._mesh = _to_jax_mesh(meshes)
        if shard_dims is None:
            axes: Sequence[str] = (self._mesh.axis_names[0],)
        elif isinstance(shard_dims, str):
            axes = (shard_dims,)
        elif isinstance(shard_dims, int):
            axes = (self._mesh.axis_names[shard_dims],)
        else:
            axes = tuple(a if isinstance(a, str) else self._mesh.axis_names[a]
                         for a in shard_dims)
        for a in axes:
            if a not in self._mesh.axis_names:
                raise ValueError(f"shard_dims axis {a!r} not in mesh axes "
                                 f"{self._mesh.axis_names}")
        self._axes = axes
        self._input_keys = set(input_keys) if input_keys else None
        # per-host pre-split datasets would double-shard under a global
        # device_put; unsupported in the single-controller SPMD model
        if is_dataset_splitted:
            raise NotImplementedError(
                "is_dataset_splitted=True: under SPMD the loader yields the "
                "global batch and sharding places it; pre-split per-host "
                "loading is handled by io.DistributedBatchSampler instead")

    def _place(self, leaf):
        import numpy as np
        if not isinstance(leaf, (jax.Array, np.ndarray)) or jax.numpy.ndim(
                leaf) == 0:
            return leaf
        n = int(np.prod([self._mesh.shape[a] for a in self._axes]))
        if leaf.shape[0] % n:
            raise ValueError(
                f"batch dim {leaf.shape[0]} is not divisible by the "
                f"{'x'.join(self._axes)} axis size {n} (XLA shards evenly); "
                "use DataLoader(drop_last=True) or pad the final batch")
        spec = P(self._axes[0] if len(self._axes) == 1 else self._axes)
        return jax.device_put(leaf, NamedSharding(self._mesh, spec))

    def _shard_batch(self, batch):
        if isinstance(batch, dict):
            return {k: (jax.tree.map(self._place, v)
                        if self._input_keys is None or k in self._input_keys
                        else v)
                    for k, v in batch.items()}
        return jax.tree.map(self._place, batch)

    def __iter__(self):
        for batch in self._dl:
            yield self._shard_batch(batch)

    def __len__(self):
        return len(self._dl)


def shard_dataloader(dataloader, meshes=None, input_keys=None,
                     shard_dims=None, is_dataset_splitted=False):
    return ShardDataloader(dataloader, meshes, input_keys, shard_dims,
                           is_dataset_splitted)


def shard_layer(layer, mesh=None, shard_fn=None):
    """Apply a per-parameter shard_fn(name, param) -> placements, or leave
    parameters replicated on the mesh."""
    jmesh = _to_jax_mesh(mesh)
    for name, p in list(layer.named_parameters()):
        placements = shard_fn(name, p) if shard_fn else [Replicate()]
        layer._assign_by_path(name, shard_tensor(p, jmesh, placements))
    return layer
