"""``paddle_tpu.distributed.spawn``: single-node multiprocess launch API.

Reference: python/paddle/distributed/spawn.py — programmatic alternative to
the launch CLI; spawns nprocs local processes running fn(rank, *args) with
the env protocol set.

TPU note: a TPU host normally runs ONE process driving all local chips, so
on real hardware nprocs defaults to 1 and spawn exists mainly for porting
parity and CPU-mesh testing (each child gets its own virtual device set via
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Optional, Sequence

from ..launch.store import free_port


def _worker(fn, rank: int, nprocs: int, coordinator: str, args, err_q):
    os.environ["PDTPU_PROCESS_ID"] = str(rank)
    os.environ["PDTPU_NUM_PROCESSES"] = str(nprocs)
    os.environ["PDTPU_COORDINATOR"] = coordinator
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    try:
        fn(rank, *args)
    except Exception:  # noqa: BLE001 — relay to parent
        err_q.put((rank, traceback.format_exc()))
        raise


def spawn(fn, args: Sequence = (), nprocs: int = 1,
          join: bool = True, daemon: bool = False,
          coordinator: Optional[str] = None):
    """Spawn ``nprocs`` processes running ``fn(rank, *args)``."""
    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    if nprocs == 1 and join:
        # fast path, in-process (matches reference behaviour for nprocs=1);
        # still sets the env protocol so fn sees the same contract as the
        # subprocess path
        for k, v in (("PDTPU_PROCESS_ID", "0"), ("PDTPU_NUM_PROCESSES", "1"),
                     ("PDTPU_COORDINATOR", coordinator),
                     ("PADDLE_TRAINER_ID", "0"), ("PADDLE_TRAINERS_NUM", "1")):
            os.environ[k] = v
        fn(0, *args)
        return None
    ctx = mp.get_context("spawn")
    err_q = ctx.SimpleQueue()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(fn, rank, nprocs, coordinator, tuple(args),
                              err_q),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    for p in procs:
        p.join()
    fails = [p.exitcode for p in procs if p.exitcode]
    if fails:
        msg = ""
        while not err_q.empty():
            rank, tb = err_q.get()
            msg += f"\n--- rank {rank} ---\n{tb}"
        raise RuntimeError(f"spawn: {len(fails)} process(es) failed{msg}")
    return None
