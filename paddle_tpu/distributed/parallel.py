"""``paddle.DataParallel`` parity (reference:
python/paddle/distributed/parallel.py).

In the reference, DataParallel hooks a gradient Reducer into eager
backward: every ``loss.backward()`` all-reduces grads across ranks, and
``no_sync()`` suppresses that all-reduce so grads accumulate locally for
gradient accumulation.

TPU redesign: under single-controller SPMD the cross-device grad
reduction is part of the compiled step (XLA derives it from the sharded
batch — SURVEY §7.0 dissolves the Reducer).  The wrapper therefore
carries the *contract*, not the transport:

- ``DataParallel(model)`` validates/uses the dp environment and delegates
  forward/state to the wrapped Layer (checkpoints stay wrapper-free, like
  the reference's ``state_dict`` delegation);
- ``no_sync()`` flips a flag that ``jit.TrainStep`` reads at dispatch
  time: inside the context a step ACCUMULATES gradients into the train
  state and skips the optimizer; the first step outside folds the
  accumulated grads in and applies the update.  Reference semantics —
  grads SUM across microsteps, so callers scale the loss by
  1/accumulate_steps exactly as they would with the reference.
"""

from __future__ import annotations

import contextlib

from ..nn.layer import Layer
from . import fleet


class DataParallel(Layer):
    """Layer wrapper routing training to the mesh's dp axis.

    Usage (compiled path)::

        model = paddle_tpu.DataParallel(model)
        step = TrainStep(model, loss_fn, opt, mesh=mesh)  # accumulation on
        with model.no_sync():
            state, _ = step(state, micro1)   # grads staged, no update
        state, m = step(state, micro2)       # folds staged grads, updates
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        # reference ignores these on single-process too; kept for signature
        # parity (comm buffers have no meaning under XLA collectives)
        del strategy, comm_buffer_size, last_comm_buffer_size
        del find_unused_parameters, group
        self._layers = layers
        self._grad_sync = True

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Accumulate grads without applying the optimizer (reference:
        DataParallel.no_sync suppressing the Reducer all-reduce)."""
        prev = self._grad_sync
        self._grad_sync = False
        try:
            yield
        finally:
            self._grad_sync = prev

    def scale_loss(self, loss):
        """Reference API: pre-backward loss scaling hook. The SPMD grad of
        a mean loss over the sharded global batch is already the mean —
        identity here."""
        return loss

    # checkpoint surface stays wrapper-free (reference behavior)
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    load_dict = set_state_dict


def init_parallel_env(*args, **kwargs):
    from .communication import init_parallel_env as _impl
    return _impl(*args, **kwargs)


def get_rank(*args, **kwargs):
    from .communication import get_rank as _impl
    return _impl(*args, **kwargs)


def get_world_size(*args, **kwargs):
    from .communication import get_world_size as _impl
    return _impl(*args, **kwargs)
