"""Mixture-of-Experts with expert parallelism (``ep`` mesh axis).

Reference surface (SURVEY.md §2.5): MoELayer + gates
(python/paddle/incubate/distributed/models/moe/moe_layer.py,
gate/gshard_gate.py, gate/switch_gate.py, gate/naive_gate.py), capacity +
token dropping via the fused CUDA helper ops (number_count,
limit_by_capacity, prune_gate_by_capacity, random_routing), grouped NCCL
all-to-all dispatch/combine, and the expert-aware grad clip
(moe/grad_clip.py).

TPU redesign: the reference routes tokens with scatter/gather CUDA kernels
and explicit alltoall calls.  Here routing is the GShard einsum
formulation — dense one-hot dispatch/combine tensors contracted on the MXU
— and expert placement is a sharding annotation: expert parameters are
stacked on a leading expert axis sharded over ``ep``, the dispatched
activations [E, C, H] carry the same constraint, and XLA emits the
all-to-all exchange.  The helper ops become one-liners on cumsums
(number_count/limit_by_capacity below) instead of kernels.

Capacity semantics match the reference: each expert processes at most
``capacity_factor * tokens / num_experts`` tokens; overflow tokens are
dropped (their combine weight is zero, so they pass through the residual
path of the surrounding block).

Grad-clip note: expert params are global sharded arrays under GSPMD, so
``ClipGradByGlobalNorm`` already reduces their squared norms globally —
the reference's special expert-aware clip exists only because its expert
params are process-local.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core import random as prandom
from ..nn.layer import Layer, ParamMeta
from .mp_layers import constrain as _constrain

_SEP = "__"


# ---------------------------------------------------------------------------
# helper "ops" (reference: fused CUDA kernels, here cumsum one-liners)
# ---------------------------------------------------------------------------

def number_count(gate_idx, upper_range):
    """Tokens routed to each expert (reference: number_count op)."""
    return jnp.sum(jax.nn.one_hot(gate_idx, upper_range, dtype=jnp.int32),
                   axis=0)


def limit_by_capacity(expert_mask, capacity):
    """Zero mask entries beyond each expert's capacity, preserving token
    order (reference: limit_by_capacity + prune_gate_by_capacity ops).
    ``expert_mask``: [N, E] one-hot; returns (kept_mask, position_in_expert).
    """
    pos = jnp.cumsum(expert_mask, axis=0) * expert_mask - expert_mask
    kept = expert_mask * (pos < capacity)
    return kept, pos


# ---------------------------------------------------------------------------
# gates
# ---------------------------------------------------------------------------

class NaiveGate(Layer):
    """Linear router returning (combine_weights, dispatch_mask, aux_loss).

    Subclasses implement ``route(probs, capacity)``.
    """

    top_k = 2

    def __init__(self, d_model: int, num_experts: int,
                 capacity_factor: float = 1.25,
                 eval_capacity_factor: Optional[float] = None):
        # eval_capacity_factor None (default) → dropless eval routing; set
        # it explicitly to cap eval capacity like training
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor  # None = dropless
        self.weight = self.create_parameter(
            (d_model, num_experts),
            default_initializer=lambda k, s, d: jax.random.uniform(
                k, s, d, -1 / math.sqrt(d_model), 1 / math.sqrt(d_model)))

    def capacity(self, num_tokens: int) -> int:
        if not self.training and self.eval_capacity_factor is None:
            # eval default: DROPLESS routing. Inference must not drop
            # tokens, and — critically for KV-cache serving — capacity from
            # the per-call token count would make a one-token decode step
            # route differently from the full-prefix recompute it must
            # reproduce (the generate() greedy-identity contract).
            return num_tokens
        f = self.capacity_factor if self.training else self.eval_capacity_factor
        return max(int(f * num_tokens * self.top_k / self.num_experts), 4)

    def forward(self, x):
        """x: [N, H] tokens → (combine [N,E,C], dispatch [N,E,C] bool, aux)."""
        logits = (x.astype(jnp.float32) @
                  self.weight.astype(jnp.float32))        # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        return self.route(probs, self.capacity(x.shape[0]))

    def route(self, probs, capacity):
        raise NotImplementedError


class SwitchGate(NaiveGate):
    """Top-1 routing (Switch Transformer; reference: switch_gate.py)."""

    top_k = 1

    def route(self, probs, capacity):
        E = self.num_experts
        idx1 = jnp.argmax(probs, axis=-1)                 # [N]
        mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
        # load-balancing aux loss (mean prob × mean assignment, scaled by E)
        aux = E * jnp.sum(jnp.mean(probs, axis=0) * jnp.mean(mask1, axis=0))
        kept1, pos1 = limit_by_capacity(mask1, capacity)
        gate1 = jnp.sum(probs * kept1, axis=-1)           # [N]
        loc1 = jax.nn.one_hot(jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32),
                              capacity, dtype=probs.dtype)  # [N, C]
        combine = gate1[:, None, None] * kept1[:, :, None] * loc1[:, None, :]
        return combine, combine > 0, aux


class GShardGate(NaiveGate):
    """Top-2 routing with random second-expert admission (gshard_gate.py)."""

    top_k = 2

    def __init__(self, *args, random_routing: bool = True, **kwargs):
        super().__init__(*args, **kwargs)
        self.random_routing = random_routing

    def route(self, probs, capacity):
        E = self.num_experts
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
        probs_wo1 = probs * (1 - mask1)
        idx2 = jnp.argmax(probs_wo1, axis=-1)
        mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)

        aux = E * jnp.sum(jnp.mean(probs, axis=0) * jnp.mean(mask1, axis=0))

        gate1 = jnp.sum(probs * mask1, axis=-1)
        gate2 = jnp.sum(probs * mask2, axis=-1)
        if self.random_routing and self.training:
            # admit the 2nd expert with prob 2*gate2 (GShard §3.2): biases
            # traffic toward confident second choices
            u = jax.random.uniform(prandom.next_key("moe_gate"),
                                   gate2.shape, gate2.dtype)
            mask2 = mask2 * (u < 2.0 * gate2).astype(mask2.dtype)[:, None]

        kept1, pos1 = limit_by_capacity(mask1, capacity)
        # 2nd-choice tokens queue behind ALL 1st-choice tokens per expert
        pos2_base = jnp.sum(mask1, axis=0, keepdims=True)
        pos2 = (jnp.cumsum(mask2, axis=0) - mask2) * mask2 + pos2_base * mask2
        kept2 = mask2 * (pos2 < capacity)

        gate1 = jnp.sum(probs * kept1, axis=-1)
        gate2 = jnp.sum(probs * kept2, axis=-1)
        denom = jnp.maximum(gate1 + gate2, 1e-9)
        gate1, gate2 = gate1 / denom, gate2 / denom

        def _combine(gate, kept, pos, mask):
            loc = jax.nn.one_hot(
                jnp.sum(pos * mask, axis=-1).astype(jnp.int32), capacity,
                dtype=probs.dtype)
            return gate[:, None, None] * kept[:, :, None] * loc[:, None, :]

        combine = (_combine(gate1, kept1, pos1, mask1) +
                   _combine(gate2, kept2, pos2, mask2))
        return combine, combine > 0, aux


GATES = {"naive": SwitchGate, "switch": SwitchGate, "gshard": GShardGate}


# ---------------------------------------------------------------------------
# MoE layer
# ---------------------------------------------------------------------------

class MoELayer(Layer):
    """Expert-parallel MoE (reference: moe_layer.py MoELayer).

    ``experts`` is a factory building one expert Layer (any [..., H] →
    [..., H] module); ``num_experts`` instances are built with independent
    init and their parameters stacked on a leading expert axis sharded over
    ``ep``.

    Aux-loss contract (jax-native — NO global side channel, it would leak
    tracers across checkpoint/scan/vmap boundaries): after ``forward``
    returns, ``self.aux_loss`` holds the load-balancing loss of THAT call.
    It is valid only at the same trace level, i.e. read it immediately
    after calling the layer (as MixtralDecoderLayer does) and thread it
    outward through your function's outputs.  ``moe_group`` and
    ``recompute_interval`` are accepted for reference-signature parity; the
    expert group is the mesh's ``ep`` axis and recompute is the enclosing
    block's concern.
    """

    def __init__(self, d_model: int, expert: Callable[[], Layer],
                 num_experts: int, gate="gshard", top_k: Optional[int] = None,
                 capacity_factor: float = 1.25, moe_group=None,
                 recompute_interval: int = 0):
        super().__init__()
        self.num_experts = num_experts
        if isinstance(gate, str):
            self.gate = GATES[gate](d_model, num_experts,
                                    capacity_factor=capacity_factor)
        else:
            self.gate = gate
        if top_k is not None and top_k != self.gate.top_k:
            raise ValueError(
                f"top_k={top_k} conflicts with gate {type(self.gate).__name__}"
                f" (top_k={self.gate.top_k}); pick gate='switch' for top-1 "
                "or gate='gshard' for top-2")
        instances = [expert() for _ in range(num_experts)]
        object.__setattr__(self, "template", instances[0])
        per_exp = [dict(inst.named_parameters()) for inst in instances]
        metas = instances[0].param_meta()
        self._param_names = list(per_exp[0].keys())
        for name in self._param_names:
            first = per_exp[0][name]
            if isinstance(first, jax.ShapeDtypeStruct):
                # nn.meta_init() construction (deviceless memory proofs):
                # stack abstractly — jnp.stack rejects ShapeDtypeStructs
                stacked = jax.ShapeDtypeStruct(
                    (num_experts,) + tuple(first.shape), first.dtype)
            else:
                stacked = jnp.stack([pe[name] for pe in per_exp], axis=0)
            meta = metas.get(name, ParamMeta())
            base = list(meta.partition) if meta.partition is not None else []
            base += [None] * (stacked.ndim - 1 - len(base))
            self._register_parameter(
                name.replace(".", _SEP), stacked,
                ParamMeta(trainable=meta.trainable,
                          partition=P("ep", *base), is_bias=meta.is_bias))
        self.aux_loss = 0.0

    def _extra_mode_layers(self):
        # train()/eval() must reach the expert template even though it is
        # outside the sublayer registry (its params are superseded by the
        # stacked arrays)
        return (self.template,)

    def stacked_params(self):
        return {n: getattr(self, n.replace(".", _SEP))
                for n in self._param_names}

    def forward(self, x):
        """x: [..., H] → [..., H]; routing over the flattened token dim."""
        from ..nn.layer import _swapped_params
        shape = x.shape
        H = shape[-1]
        tokens = x.reshape(-1, H)                       # [N, H]
        combine, dispatch, aux = self.gate(tokens)      # [N,E,C] ×2, scalar
        self.aux_loss = aux  # same-trace readback only (see class docstring)

        # dispatch: [E, C, H] — expert-sharded; XLA emits the all-to-all
        expert_in = jnp.einsum("nec,nh->ech",
                               dispatch.astype(x.dtype), tokens)
        expert_in = _constrain(expert_in, "ep")

        params = self.stacked_params()

        def one_expert(p, h):
            with _swapped_params(self.template, p):
                return self.template(h)

        expert_out = jax.vmap(one_expert)(params, expert_in)   # [E, C, H]
        expert_out = _constrain(expert_out, "ep")

        out = jnp.einsum("ech,nec->nh", expert_out,
                         combine.astype(x.dtype))
        return out.reshape(shape)


def moe_dispatch(x, combine_weights, dispatch_mask):
    """Functional dispatch (incubate.nn.functional.moe_dispatch parity)."""
    return jnp.einsum("nec,nh->ech", dispatch_mask.astype(x.dtype), x)


def moe_combine(expert_out, combine_weights):
    """Functional combine (incubate.nn.functional.moe_combine parity)."""
    return jnp.einsum("ech,nec->nh", expert_out,
                      combine_weights.astype(expert_out.dtype))
