"""Round-4 distributed tail: object collectives, gloo host group,
ParallelEnv, Placement, split, shard_optimizer, unshard_dtensor.

Reference: python/paddle/distributed/{parallel,collective}.py and
auto_parallel/api.py (SURVEY §2.4 Python comm API row).
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .auto import Partial, Replicate, Shard
from .communication import (ReduceOp, all_gather, all_reduce, broadcast,
                            get_rank, get_world_size, scatter)


# ---------------------------------------------------------------------------
# object collectives (pickle over the byte-tensor collectives, exactly the
# reference's _convert_object_to_tensor scheme)
# ---------------------------------------------------------------------------

def _padded_size(nbytes: int, group=None) -> int:
    """Collective byte-buffer size for an ``nbytes`` pickle: the next
    256-byte multiple, MAX-REDUCED across the group's ranks (ADVICE r5).

    The reference sizes the tensor to the object (ADVICE r4); small
    objects no longer move a fixed 1 MB and large ones are no longer
    rejected.  In the single-controller SPMD model the local pickle is
    identical on every rank by construction, so the max-reduce is a
    cheap identity — but a per-rank-divergent payload (a bug today, a
    multi-process object path tomorrow) now pads every rank to the
    global maximum, so the byte collective runs with agreeing shapes
    and the truth surfaces in the unpickled objects, instead of an XLA
    shape mismatch (or silent corruption) downstream.  Explicit
    ``max_bytes`` callers (scatter) keep the loud over-budget raise in
    ``_obj_to_padded``."""
    padded = max(256, (nbytes + 255) // 256 * 256)
    try:
        agreed = int(all_reduce(jnp.asarray(padded, jnp.int32),
                                op=ReduceOp.MAX, group=group))
    except Exception:
        # no mesh / no parallel env: single-rank, local size is global
        return padded
    return max(padded, agreed)


def _obj_to_padded(obj, max_bytes=None, group=None):
    raw = pickle.dumps(obj)
    size = max_bytes if max_bytes is not None \
        else _padded_size(len(raw), group=group)
    if len(raw) > size:
        raise ValueError(f"object of {len(raw)} bytes exceeds the "
                         f"{size}-byte object-collective budget")
    buf = np.zeros((size + 8,), np.uint8)
    buf[:8] = np.frombuffer(np.int64(len(raw)).tobytes(), np.uint8)
    buf[8:8 + len(raw)] = np.frombuffer(raw, np.uint8)
    return jnp.asarray(buf)


def _padded_to_obj(buf):
    b = np.asarray(buf).astype(np.uint8)
    n = int(np.frombuffer(b[:8].tobytes(), np.int64)[0])
    return pickle.loads(b[8:8 + n].tobytes())


def all_gather_object(object_list, obj, group=None):
    """Reference: paddle.distributed.all_gather_object — every rank
    contributes one picklable object; all ranks receive all of them."""
    gathered = []
    all_gather(gathered, _obj_to_padded(obj, group=group), group=group)
    object_list.extend(_padded_to_obj(t) for t in gathered)
    return object_list


def broadcast_object_list(object_list, src=0, group=None):
    """Reference: paddle.distributed.broadcast_object_list (in place)."""
    if not object_list:
        return object_list
    # one group max-reduce over the local max, not one per element (the
    # scatter path's convention); elements then share one buffer size
    common = _padded_size(max(len(pickle.dumps(o)) for o in object_list),
                          group=group)
    for i, obj in enumerate(object_list):
        t = broadcast(_obj_to_padded(obj, max_bytes=common), src=src,
                      group=group)
        object_list[i] = _padded_to_obj(t)
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Reference: paddle.distributed.scatter_object_list.

    SPMD note: every rank runs the same program over global values, so —
    unlike the reference's per-rank processes — ``in_object_list`` must
    be passed on ALL ranks (it is the same global list everywhere); the
    reference's pass-None-on-non-src convention has no meaning here."""
    if in_object_list is None:
        raise ValueError(
            "scatter_object_list: in_object_list must be provided on every "
            "rank — SPMD programs see the same global inputs (the "
            "reference's None-on-non-src convention does not apply)")
    # one shared buffer size: scatter stacks the buffers, so DIFFERENT
    # objects (the whole point of scatter) must pad to the max pickle;
    # one group max-reduce over the local max, not one per element
    common = _padded_size(max(len(pickle.dumps(o)) for o in in_object_list),
                          group=group)
    tensors = [_obj_to_padded(o, max_bytes=common) for o in in_object_list]
    got = scatter(None, tensor_list=tensors, src=src, group=group)
    if got is None:  # world of 1 (no comm context): src keeps its element
        out_object_list.append(in_object_list[src])
        return out_object_list
    got = np.asarray(got)
    if got.ndim == 2:  # eager global form keeps the group dim (see scatter)
        got = got[get_rank(group)]
    out_object_list.append(_padded_to_obj(got))
    return out_object_list


# ---------------------------------------------------------------------------
# process-group lifecycle / introspection
# ---------------------------------------------------------------------------

def is_available() -> bool:
    """Reference: paddle.distributed.is_available."""
    return True


def get_backend(group=None) -> str:
    """Reference: paddle.distributed.get_backend — the comm transport.
    XLA emits collectives over ICI/DCN on TPU and shared-memory on the
    CPU mesh; 'XLA' names both (NCCL/GLOO dissolve per SURVEY §7.3)."""
    return "XLA"


def get_group(id=0):
    """Reference: paddle.distributed.get_group — group registry lookup."""
    from .communication import Group
    reg = getattr(get_group, "_registry", None)
    if reg and id in reg:
        return reg[id]
    return Group(("dp",))


def destroy_process_group(group=None):
    """Reference: paddle.distributed.destroy_process_group — tear down the
    bootstrap (jax.distributed) connection; mesh-axis groups are pure
    values and need no teardown.  Destroying a SUBGROUP (``group`` given,
    valid reference usage) is therefore a no-op here — it must NOT tear
    down the global bootstrap for everyone (ADVICE r4)."""
    if group is not None:
        return
    try:
        jax.distributed.shutdown()
    except Exception:
        pass  # not initialized — matches the reference's idempotent call


def wait(tensor, group=None, use_calc_stream=True):
    """Reference: paddle.distributed.wait — block until the tensor's
    producing computation (including collectives) lands."""
    return jax.block_until_ready(tensor)


# ---------------------------------------------------------------------------
# gloo host group — CPU-side barrier/bootstrap over the native TCPStore
# (reference: paddle.distributed.gloo_init_parallel_env / gloo_barrier /
# gloo_release over an actual gloo context)
# ---------------------------------------------------------------------------

_gloo = {"store": None, "rank": 0, "world": 1, "gen": 0}


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint=None):
    from ..launch.store import TCPStore
    ep = server_endpoint or os.environ.get("PADDLE_GLOO_HTTP_ENDPOINT",
                                           "127.0.0.1:6170")
    _gloo["store"] = TCPStore(ep, is_master=(int(rank_id) == 0))
    _gloo["rank"], _gloo["world"] = int(rank_id), int(rank_num)
    _gloo["gen"] = 0


def gloo_barrier():
    st = _gloo["store"]
    if st is None:
        raise RuntimeError("gloo_barrier: call gloo_init_parallel_env first")
    _gloo["gen"] += 1
    key = f"gloo/barrier/{_gloo['gen']}"
    st.add(key, 1)
    import time
    deadline = time.time() + 300.0
    while time.time() < deadline:
        v = st.get(key)
        if v is not None and int(v) >= _gloo["world"]:
            return
        time.sleep(0.01)
    raise TimeoutError("gloo_barrier timed out")


def gloo_release():
    st = _gloo.pop("store", None)
    _gloo.update(store=None, rank=0, world=1, gen=0)
    if st is not None and hasattr(st, "close"):
        st.close()


# ---------------------------------------------------------------------------
# legacy env / placement / strategy surface
# ---------------------------------------------------------------------------

class ParallelEnv:
    """Reference: paddle.distributed.ParallelEnv — env-derived rank info
    (the pre-fleet legacy API; still widely used in ported scripts)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        sel = os.environ.get("FLAGS_selected_gpus") or \
            os.environ.get("TPU_VISIBLE_DEVICES") or "0"
        return int(sel.split(",")[0])

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", self.rank))


class _PlacementMeta(type):
    def __instancecheck__(cls, obj):
        return isinstance(obj, (Shard, Replicate, Partial))


class Placement(metaclass=_PlacementMeta):
    """Reference: paddle.distributed.Placement — the common base of
    Shard/Replicate/Partial.  isinstance() works against all three."""


def Strategy(config=None):
    """Reference: paddle.distributed.Strategy (auto-parallel config) —
    the same knobs live on fleet.DistributedStrategy here."""
    from .fleet import DistributedStrategy
    s = DistributedStrategy()
    for k, v in (config or {}).items():
        setattr(s, k, v)
    return s


# ---------------------------------------------------------------------------
# split / shard_optimizer / unshard_dtensor
# ---------------------------------------------------------------------------

def split(x, size, operation, axis=0, num_partitions=None, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference: paddle.distributed.split — build a model-parallel
    linear/embedding sharded along ``axis`` over the mp mesh axis.
    Delegates to the mp_layers implementations (SURVEY §2.5 TP row)."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f,
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(in_f, out_f)
        return layer(x)
    if operation == "embedding":
        vocab, dim = size
        layer = VocabParallelEmbedding(vocab, dim)
        return layer(x)
    raise ValueError("operation must be 'linear' or 'embedding'")


def shard_optimizer(optimizer, shard_fn=None):
    """Reference: paddle.distributed.shard_optimizer — ZeRO-style
    partitioning of optimizer states over the data-parallel axis; the
    stage-1 sharded wrapper implements exactly that."""
    from .sharding import DygraphShardingOptimizer
    del shard_fn  # partition policy is the dp-axis ZeRO-1 layout
    return DygraphShardingOptimizer(optimizer)


def unshard_dtensor(dist_tensor):
    """Reference: paddle.distributed.unshard_dtensor — gather a sharded
    array into a fully-replicated one."""
    return jnp.asarray(np.asarray(dist_tensor))
