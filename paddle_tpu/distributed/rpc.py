"""``paddle.distributed.rpc`` parity: minimal point-to-point RPC.

Reference: python/paddle/distributed/rpc/ (init_rpc, rpc_sync, rpc_async,
get_worker_info, shutdown) over brpc (SURVEY §2.7).

TPU redesign: brpc is replaced by a small threaded TCP server per worker
(same length-prefixed wire helpers as the rendezvous store) with pickled
callables — RPC here is control-plane only (dataset coordination, eval
dispatch); tensor traffic belongs on ICI collectives, not RPC, exactly as
in the reference's intended usage. Worker discovery rides the TCPStore.

Trust model (same as the reference): pickle over sockets is only safe
among the mutually-trusting hosts of one training job.
"""

from __future__ import annotations

import concurrent.futures as cf
import pickle
import socket
import socketserver
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..launch.store import TCPStore, _pack, _unpack, free_port


@dataclass
class WorkerInfo:
    name: str
    rank: int
    endpoint: str


class _RpcState:
    def __init__(self):
        self.name: Optional[str] = None
        self.rank = -1
        self.world_size = 0
        self.store: Optional[TCPStore] = None
        self.server: Optional[socketserver.ThreadingTCPServer] = None
        self.workers: Dict[str, WorkerInfo] = {}
        self.pool = cf.ThreadPoolExecutor(max_workers=8,
                                          thread_name_prefix="pdtpu-rpc")
        self.conn_lock = threading.Lock()
        self.conns: Dict[str, socket.socket] = {}
        self.send_locks: Dict[str, threading.Lock] = {}


_global = _RpcState()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            while True:
                fields = _unpack(self.request)
                try:
                    fn, args, kwargs = pickle.loads(fields[0])
                    result = (True, fn(*args, **kwargs))
                except Exception as e:  # noqa: BLE001 — relay to caller
                    result = (False, e)
                try:
                    payload = pickle.dumps(result)
                except Exception as e:  # unpicklable result/exception:
                    # still answer (with a picklable error) so the caller
                    # gets a real message instead of a dead connection
                    payload = pickle.dumps(
                        (False, RuntimeError(
                            f"rpc result not picklable: {e!r}")))
                self.request.sendall(_pack(payload))
        except (ConnectionError, OSError, EOFError):
            return


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's RPC server and discover all peers by name."""
    import os
    g = _global
    if g.server is not None:
        raise RuntimeError("rpc already initialized")
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", 0))
    world_size = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER") or f"127.0.0.1:{free_port()}"
    g.name, g.rank, g.world_size = name, rank, world_size
    g.store = TCPStore(master_endpoint, is_master=(rank == 0))

    srv = socketserver.ThreadingTCPServer(("0.0.0.0", 0), _Handler)
    srv.daemon_threads = True
    g.server = srv
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="pdtpu-rpc-server").start()

    ip = _routable_ip()
    g.store.set(f"rpc/worker/{rank}",
                pickle.dumps(WorkerInfo(name, rank, f"{ip}:{port}")))
    for r in range(world_size):
        try:
            raw = g.store.wait(f"rpc/worker/{r}", timeout=300.0)
        except TimeoutError:
            raise TimeoutError(
                f"init_rpc: worker rank {r} never registered (crashed "
                f"during startup, or wrong master_endpoint?)")
        info: WorkerInfo = pickle.loads(raw)
        g.workers[info.name] = info


def _routable_ip() -> str:
    """Advertise an address peers can actually reach: gethostbyname often
    yields 127.0.1.1 on Debian-style /etc/hosts, so prefer the interface a
    routed UDP socket binds to."""
    try:
        ip = socket.gethostbyname(socket.gethostname())
    except OSError:
        ip = "127.0.0.1"
    if ip.startswith("127."):
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect(("10.255.255.255", 1))  # no packets sent
                ip = s.getsockname()[0]
        except OSError:
            pass
    return ip


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    g = _global
    if g.server is None:
        raise RuntimeError("call init_rpc first")
    return g.workers[name or g.name]


def get_all_worker_infos():
    return sorted(_global.workers.values(), key=lambda w: w.rank)


def _send_lock(name: str) -> threading.Lock:
    g = _global
    with g.conn_lock:
        return g.send_locks.setdefault(name, threading.Lock())


def _conn_to(name: str) -> socket.socket:
    """Cached connection to a peer. The (possibly slow) connect happens
    under the per-destination send lock, NOT the global map lock, so a slow
    peer doesn't stall RPC traffic to every other destination."""
    g = _global
    with g.conn_lock:
        s = g.conns.get(name)
    if s is None:
        info = g.workers[name]
        host, port = info.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        with g.conn_lock:
            existing = g.conns.get(name)
            if existing is not None:   # raced: keep the first, drop ours
                s.close()
                s = existing
            else:
                g.conns[name] = s
    return s


def _evict_conn(name: str) -> None:
    """Drop a desynced/broken connection so the next call reconnects —
    a timed-out request would otherwise leave its late response in the
    buffer to be read as the NEXT call's answer."""
    g = _global
    with g.conn_lock:
        s = g.conns.pop(name, None)
    if s is not None:
        try:
            s.close()
        except OSError:
            pass


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 60.0) -> Any:
    """Run fn(*args, **kwargs) on worker `to`, return its result."""
    g = _global
    if g.server is None:
        raise RuntimeError("call init_rpc first")
    payload = pickle.dumps((fn, tuple(args), dict(kwargs or {})))
    # one in-flight request per destination: serialize senders; connect
    # under the same lock (slow peers only stall their own destination)
    with _send_lock(to):
        s = _conn_to(to)
        try:
            s.settimeout(timeout)
            s.sendall(_pack(payload))
            fields = _unpack(s)
        except Exception:
            _evict_conn(to)
            raise
    ok, result = pickle.loads(fields[0])
    if not ok:
        raise result
    return result


def rpc_async(to: str, fn, args=(), kwargs=None, timeout: float = 60.0):
    """Like rpc_sync but returns a Future (``.wait()`` paddle alias)."""
    fut = _global.pool.submit(rpc_sync, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle Future API: fut.wait()
    return fut


def shutdown(graceful: bool = True) -> None:
    g = _global
    if g.server is None:
        return
    if graceful and g.store is not None:
        g.store.barrier("rpc/shutdown", g.world_size, timeout=60.0)
    with g.conn_lock:
        for s in g.conns.values():
            try:
                s.close()
            except OSError:
                pass
        g.conns.clear()
        g.send_locks.clear()
    g.server.shutdown()
    g.server.server_close()
    g.server = None
    if g.store is not None:
        g.store.close()
        g.store = None
    g.workers.clear()
    g.name = None
