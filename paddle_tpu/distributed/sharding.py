"""ZeRO-style sharded training (``paddle.distributed.sharding`` parity).

Reference (SURVEY.md §2.5): stage-1
meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py
(DygraphShardingOptimizer: optimizer states partitioned over the sharding
group, grads reduced to their owner rank, updated params broadcast),
stage-2 meta_parallel/sharding/group_sharded_optimizer_stage2.py +
group_sharded_stage2.py (gradient partitioning), stage-3
group_sharded_stage3.py (parameter partitioning with pre-forward allgather
/ post-backward release + CPU offload), entry point
python/paddle/distributed/sharding/group_sharded.py
(``group_sharded_parallel(model, optimizer, level="p_g_os")``).

TPU redesign: the reference hand-chunks every tensor and choreographs
reduce/broadcast/allgather/release by rank.  Under GSPMD the same physics
is a *sharding annotation per stage*:

- stage 1 ("os"):   optimizer states sharded over the zero axes; XLA emits
  the reduce + per-shard update + implicit gather the reference codes by
  hand.
- stage 2 ("os_g"): + gradients constrained to the same sharding → the
  grad all-reduce becomes a reduce-scatter, each rank updates its shard,
  params all-gather on use (ZeRO-2's exact communication volume).
- stage 3 ("p_g_os"): + parameters stored sharded; XLA's scheduler decides
  gather/release timing (SURVEY.md §7.2 — validated empirically rather
  than choreographed).
- ``offload=True``: optimizer states live in host memory
  (``memory_kind="pinned_host"``); XLA inserts the H2D/D2H transfers the
  reference's CPU-adam path does manually.  TPU-only; ignored with a
  warning elsewhere.

All of it executes inside the one compiled TrainStep — the per-stage
classes below exist for API parity and carry the chosen stage to the step
compiler.
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax

from . import fleet

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Shard model training over the sharding axis at the given level.

    Returns ``(model, optimizer, scaler)`` like the reference.  The level
    is recorded on the optimizer; ``jit.TrainStep`` reads it (unless an
    explicit ``zero_stage`` overrides) and applies the corresponding
    sharding specs.  Extra knobs of the reference that control its manual
    bucketing/communication (buffer_max_size, segment_size, sync_comm) are
    accepted for signature parity and ignored — XLA owns scheduling.
    """
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {sorted(_LEVELS)}, got {level!r}")
    stage = _LEVELS[level]
    if stage == 1:
        optimizer = DygraphShardingOptimizer(optimizer, offload=offload)
    elif stage == 2:
        optimizer = GroupShardedOptimizerStage2(optimizer, offload=offload)
    elif stage == 3:
        optimizer = _Stage3ShardedOptimizer(optimizer, offload=offload)
        model = GroupShardedStage3(model, optimizer, offload=offload)
    return model, optimizer, scaler


def _check_offload(offload: bool) -> bool:
    if not offload:
        return False
    if jax.default_backend() != "tpu":
        warnings.warn("offload=True needs TPU host memory spaces; ignored "
                      f"on backend {jax.default_backend()!r}")
        return False
    return True


class _ShardedOptimizerWrapper:
    """Delegating wrapper that pins a ZeRO stage onto an optimizer."""

    _stage = 1

    def __init__(self, inner, offload=False):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_zero_stage", self._stage)
        object.__setattr__(self, "_zero_offload", _check_offload(offload))

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        if name.startswith("_zero"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)


class DygraphShardingOptimizer(_ShardedOptimizerWrapper):
    """Stage-1 parity: optimizer states sharded over the sharding axes."""

    _stage = 1


class GroupShardedOptimizerStage2(_ShardedOptimizerWrapper):
    """Stage-2 parity: + gradients sharded (reduce-scatter not all-reduce)."""

    _stage = 2


class _Stage3ShardedOptimizer(_ShardedOptimizerWrapper):
    """Stage-3 marker carrier (wrapping, not mutating, the caller's
    optimizer — the same object may drive an unsharded step elsewhere)."""

    _stage = 3


class GroupShardedStage2:
    """Reference wraps the model too at stage 2; sharding lives in the
    compiled step here, so this is a transparent pass-through kept for
    call-shape parity."""

    def __new__(cls, model, optimizer=None, **kwargs):
        return model


class GroupShardedStage3:
    """Stage-3 parity: parameters stored sharded.  Pass-through wrapper —
    param sharding is applied by TrainStep.param_specs via zero_stage=3."""

    def __new__(cls, model, optimizer=None, offload=False, **kwargs):
        return model


def zero_stage_of(optimizer, explicit: Optional[int] = None) -> int:
    """Resolve the effective ZeRO stage for the step compiler.

    An explicit argument — including an explicit 0 to force ZeRO off —
    always wins; ``None`` defers to the stage recorded by
    ``group_sharded_parallel`` (0 if none)."""
    if explicit is not None:
        return explicit
    stage = getattr(optimizer, "_zero_stage", None)
    return stage if stage is not None else 0


def zero_offload_of(optimizer) -> bool:
    return bool(getattr(optimizer, "_zero_offload", False))
