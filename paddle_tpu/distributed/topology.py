"""Hybrid-parallel topology → jax Mesh.

Reference: python/paddle/distributed/fleet/base/topology.py
(``CommunicateTopology``, ``HybridCommunicateGroup``) which builds the
Cartesian process grid over axes ["dp","pp","sharding","sep","mp"] and one
NCCL communicator per axis.  TPU-native redesign: the grid is a
``jax.sharding.Mesh`` whose axis order is chosen for the ICI torus — the
innermost (fastest-varying) axis gets physically adjacent chips, so ``mp``
(tensor parallel, latency-critical allreduce every layer) goes last, then
``sep``/``ep`` (all-to-all heavy), then ``sharding`` (ZeRO gather/scatter),
then ``dp``, with ``pp`` outermost (lowest-bandwidth p2p, can cross DCN).
There are no communicators to create: XLA collectives are addressed by axis
name.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# outermost -> innermost; mp innermost = adjacent on ICI
AXIS_ORDER = ("pp", "dp", "sharding", "ep", "sep", "mp")


@dataclass
class HybridTopology:
    """Degrees for every parallel axis (paddle ``hybrid_configs`` parity,
    plus the first-class ``sep``/``ep`` axes)."""

    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1
    ep_degree: int = 1

    def degrees(self) -> Dict[str, int]:
        return {"pp": self.pp_degree, "dp": self.dp_degree,
                "sharding": self.sharding_degree, "ep": self.ep_degree,
                "sep": self.sep_degree, "mp": self.mp_degree}

    @property
    def world_size(self) -> int:
        return math.prod(self.degrees().values())

    def build_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        ws = self.world_size
        if len(devices) < ws:
            raise ValueError(
                f"topology needs {ws} devices ({self.degrees()}), "
                f"got {len(devices)}")
        devices = devices[:ws]
        degs = self.degrees()
        shape = tuple(degs[a] for a in AXIS_ORDER)
        arr = self._device_grid(devices, shape)
        return Mesh(arr, AXIS_ORDER)

    def _device_grid(self, devices, shape):
        """Arrange devices so collectives ride the right fabric.

        On TPU, ``mesh_utils.create_device_mesh`` maps the logical grid onto
        the physical ICI torus (nearest-neighbour rings per axis); with
        multiple slices, ``create_hybrid_device_mesh`` puts ONE axis across
        the DCN — chosen as the outermost axis whose degree divides the
        slice count order (pp first, then dp, then sharding; those tolerate
        DCN latency, mp/sep/ep must stay on ICI). CPU/virtual meshes keep a
        plain deterministic reshape."""
        if getattr(devices[0], "platform", "cpu") != "tpu":
            return np.array(devices, dtype=object).reshape(shape)
        slices = {getattr(d, "slice_index", 0) for d in devices}
        n_slices = len(slices)
        if n_slices > 1:
            # This validation must NOT be swallowed by the layout fallback:
            # an mp/sep/ep ring spanning the DCN is a config error, not a
            # layout preference.
            dcn_shape = [1] * len(AXIS_ORDER)
            for cand in ("pp", "dp", "sharding"):
                i = AXIS_ORDER.index(cand)
                if shape[i] % n_slices == 0:
                    dcn_shape[i] = n_slices
                    break
            else:
                raise ValueError(
                    f"{n_slices} slices but no pp/dp/sharding degree "
                    f"divisible by the slice count in {shape}")
            try:
                from jax.experimental import mesh_utils
                ici_shape = [s // d for s, d in zip(shape, dcn_shape)]
                return mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, devices=devices)
            except (ImportError, NotImplementedError, ValueError):
                return np.array(devices, dtype=object).reshape(shape)
        try:
            from jax.experimental import mesh_utils
            return mesh_utils.create_device_mesh(shape, devices=devices)
        except (ImportError, NotImplementedError, ValueError):
            # fallback: logical order (correct, possibly suboptimal layout)
            return np.array(devices, dtype=object).reshape(shape)

    @classmethod
    def from_hybrid_configs(cls, cfg: Dict) -> "HybridTopology":
        known = {"dp_degree", "mp_degree", "pp_degree", "sharding_degree",
                 "sep_degree", "ep_degree"}
        extra = set(cfg) - known
        if extra:
            raise ValueError(f"unknown hybrid_configs keys: {sorted(extra)}")
        return cls(**{k: v for k, v in cfg.items() if k in known})

    def infer_missing(self, n_devices: int) -> "HybridTopology":
        """Fill a -1 dp_degree from the device count (paddle allows this)."""
        degs = self.degrees()
        if self.dp_degree == -1:
            rest = math.prod(v for k, v in degs.items() if k != "dp")
            self.dp_degree = n_devices // rest
        return self


class HybridCommunicateGroup:
    """Axis-rank bookkeeping over the mesh (reference:
    HybridCommunicateGroup.get_model_parallel_rank() etc.).

    Outside shard_map, ranks are derived from ``jax.process_index`` and the
    mesh's device→coordinate map; inside shard_map, use
    ``jax.lax.axis_index(axis)``.
    """

    def __init__(self, topology: HybridTopology, mesh: Mesh):
        self.topology = topology
        self.mesh = mesh
        self._coords = {}
        it = np.ndindex(mesh.devices.shape)
        for idx in it:
            self._coords[mesh.devices[idx].id] = idx

    # -- mesh handles ------------------------------------------------------

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    def active_axes(self) -> List[str]:
        return [a for a in self.axis_names if self.mesh.shape[a] > 1]

    # -- paddle-parity rank/size getters (host perspective: coordinates of
    # this process's first addressable device) --------------------------

    def _my_coord(self):
        dev = self.mesh.devices.flat[0]
        for d in self.mesh.local_devices:
            return self._coords[d.id]
        return self._coords[dev.id]

    def _axis_pos(self, axis: str) -> int:
        return self.axis_names.index(axis)

    def _rank_in(self, axis: str) -> int:
        return int(self._my_coord()[self._axis_pos(axis)])

    def get_data_parallel_rank(self):
        return self._rank_in("dp")

    def get_data_parallel_world_size(self):
        return self.axis_size("dp")

    def get_model_parallel_rank(self):
        return self._rank_in("mp")

    def get_model_parallel_world_size(self):
        return self.axis_size("mp")

    def get_stage_id(self):
        return self._rank_in("pp")

    def get_pipe_parallel_world_size(self):
        return self.axis_size("pp")

    def get_sharding_parallel_rank(self):
        return self._rank_in("sharding")

    def get_sharding_parallel_world_size(self):
        return self.axis_size("sharding")

    def get_sep_parallel_rank(self):
        return self._rank_in("sep")

    def get_sep_parallel_world_size(self):
        return self.axis_size("sep")

    def get_expert_parallel_rank(self):
        return self._rank_in("ep")

    def get_expert_parallel_world_size(self):
        return self.axis_size("ep")

    # data-axes helper: the axes a batch is sharded over
    def data_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in ("dp", "sharding") if self.axis_size(a) > 1)

    def batch_spec(self) -> P:
        axes = self.data_axes()
        return P(axes) if axes else P()
