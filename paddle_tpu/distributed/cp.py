"""Context / sequence parallelism over the "sep" mesh axis.

Reference capability (SURVEY.md §5.7): the reference core carries a dedicated
"sep" process axis (python/paddle/distributed/fleet/base/topology.py,
``sep_degree`` in hybrid_configs) used for DeepSpeed-Ulysses-style
all-to-all attention, and PaddleNLP layers ring flash attention (P2P KV
rotation) on top of the core's send/recv groups.

TPU-native redesign — both schemes become collectives inside a partial-manual
``shard_map`` over the "sep" axis (everything else — dp/mp/sharding — stays
in GSPMD auto mode, so these compose with tensor parallelism and ZeRO):

- **Ulysses** (``ulysses_attention``): activations arrive sequence-sharded
  ``[b, S/n, h, d]``; one ``lax.all_to_all`` trades the sequence shard for a
  head shard → ``[b, S, h/n, d]``; full-sequence attention runs locally (and
  therefore dispatches to the Pallas flash kernel on TPU); a second
  all-to-all restores sequence sharding.  Comm volume: 2 a2a of q/k/v/out —
  rides the ICI torus as XLA all-to-all.

- **Ring** (``ring_attention``): K/V chunks rotate around the sep ring via
  ``lax.ppermute`` while each device keeps its Q chunk; partial softmax
  statistics (running max / denominator / accumulator — the same online
  softmax as the flash kernel, at chunk granularity) merge across steps, so
  attention memory stays O(S/n · S/n) transient per step and activations are
  O(S/n).  Each ring step is ``jax.checkpoint``-ed: backward re-runs the
  rotation instead of saving per-step probability tiles.

Under single-program SPMD every device executes the same unrolled ring, so
the causal "late ranks do more work" imbalance that motivates zigzag
layouts on GPU does not change the critical path here — masked tiles are
computed-and-discarded in the same program.  A Pallas-fused ring step
(mask-skipped) is a planned kernel-pack upgrade.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from ..core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from . import fleet

NEG_INF = -1e30


def _mesh() -> Optional[Mesh]:
    hcg = fleet.get_hybrid_communicate_group()
    return hcg.mesh if hcg is not None else None


def _sep_size(mesh: Optional[Mesh], axis: str) -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def _serial_attention(q, k, v, causal, scale):
    from ..nn import functional as F
    return F.scaled_dot_product_attention(q, k, v, is_causal=causal,
                                          scale=scale)


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------

def _ring_step(carry, k_t, v_t, qg, q_pos, k_pos0, *, causal, scale, chunk):
    """One online-softmax accumulation step against the visiting KV chunk.

    qg: (b, c, hkv, g, d) grouped query; k_t/v_t: (b, c, hkv, d);
    q_pos: (c,) global query positions; k_pos0: scalar, global position of
    the visiting chunk's first key.  All statistics fp32.
    """
    m, l, acc = carry
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_t,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        k_pos = k_pos0 + jnp.arange(chunk)
        mask = q_pos[:, None] >= k_pos[None, :]          # (c, c)
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # finite NEG_INF keeps exp() well-defined for fully-masked tiles: the
    # first ring step visits the device's own (diagonal) chunk, so m is
    # already > NEG_INF when a later chunk is fully in the future
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l = l * alpha + p.sum(axis=-1)
    acc = acc * alpha[..., None].transpose(0, 3, 1, 2, 4) + jnp.einsum(
        "bhgqk,bkhd->bqhgd", p.astype(v_t.dtype), v_t,
        preferred_element_type=jnp.float32)
    return m_new, l, acc


def _ring_inner(q, k, v, rank_arr, *, axis, n, causal, scale):
    b, c, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, c, hkv, g, d)
    rank = rank_arr[0]
    q_pos = rank * c + jnp.arange(c)

    m = jnp.full((b, hkv, g, c), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, g, c), jnp.float32)
    acc = jnp.zeros((b, c, hkv, g, d), jnp.float32)

    step = jax.checkpoint(
        functools.partial(_ring_step, causal=causal, scale=scale, chunk=c))
    perm = [(i, (i + 1) % n) for i in range(n)]

    # lax.scan ring: ONE program step regardless of sep degree — compile
    # time and HLO size are sep-independent (VERDICT r2 weak #4; the
    # previous Python-unrolled loop grew both linearly with n).  The KV
    # chunks ride in the carry; ppermute rotates them each iteration (the
    # final rotation returns them home — one extra hop, dead code the
    # scheduler overlaps with the epilogue).
    def body(carry, t):
        m, l, acc, k_t, v_t = carry
        src = (rank - t) % n          # chunk index now visiting this device
        m, l, acc = step((m, l, acc), k_t, v_t, qg, q_pos, src * c)
        k_t = jax.lax.ppermute(k_t, axis, perm)
        v_t = jax.lax.ppermute(v_t, axis, perm)
        return (m, l, acc, k_t, v_t), None

    (m, l, acc, _, _), _ = jax.lax.scan(body, (m, l, acc, k, v),
                                        jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None].transpose(0, 3, 1, 2, 4)
    return out.reshape(b, c, h, d).astype(q.dtype)


def _ring_inner_flash(q, k, v, rank_arr, *, axis, n, causal, scale):
    """Ring step with the Pallas flash kernel per visiting chunk.

    Each chunk pair is one of three STATIC cases — fully visible
    (src < rank), diagonal (src == rank, ordinary causal), fully masked
    (src > rank) — selected by ``lax.switch`` at runtime, so the kernel
    never needs a traced causal offset.  Chunks merge by the kernel's
    log2-sum-exp2 statistic (``flash_attention_with_lse``; its custom VJP
    carries the lse cotangent, so autodiff through the merge is exact)."""
    from ..ops.pallas import flash_attention as fa
    b, c, h, d = q.shape
    rank = rank_arr[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def full_chunk(kv):
        return fa.flash_attention_with_lse(q, *kv, causal=False,
                                           scale=scale)

    def diag_chunk(kv):
        return fa.flash_attention_with_lse(q, *kv, causal=True,
                                           scale=scale)

    def skip_chunk(kv):
        return (jnp.zeros((b, c, h, d), q.dtype),
                jnp.full((b, h, c), NEG_INF, jnp.float32))

    def body(carry, t):
        out_acc, lse_acc, k_t, v_t = carry
        src = (rank - t) % n
        if causal:
            branch = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
            out_c, lse_c = jax.lax.switch(
                branch, [full_chunk, diag_chunk, skip_chunk], (k_t, v_t))
        else:
            out_c, lse_c = full_chunk((k_t, v_t))
        # two-way merge of normalized pieces in the base-2 domain
        m = jnp.maximum(lse_acc, lse_c)
        wa = jnp.exp2(lse_acc - m)
        wc = jnp.exp2(lse_c - m)
        denom = wa + wc
        lse_new = m + jnp.log2(denom)
        na = (wa / denom).transpose(0, 2, 1)[..., None]   # (b, c, h, 1)
        nc = (wc / denom).transpose(0, 2, 1)[..., None]
        out_new = (out_acc.astype(jnp.float32) * na
                   + out_c.astype(jnp.float32) * nc)
        k_t = jax.lax.ppermute(k_t, axis, perm)
        v_t = jax.lax.ppermute(v_t, axis, perm)
        return (out_new, lse_new, k_t, v_t), None

    out0 = jnp.zeros((b, c, h, d), jnp.float32)
    lse0 = jnp.full((b, h, c), NEG_INF, jnp.float32)
    (out, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(body), (out0, lse0, k, v), jnp.arange(n))
    return out.astype(q.dtype)


def ring_attention(q, k, v, causal=False, scale=None, axis="sep", mesh=None,
                   use_flash=None):
    """Ring flash attention over the sep axis.

    Takes GLOBAL-shaped ``[b, s, h, d]`` arrays inside jit (sequence is
    sharded over ``axis`` by the shard_map below); outside any mesh, or when
    the sep degree is 1, falls back to serial attention.  GQA supported
    (kv heads may divide q heads).

    ``use_flash=None`` (auto) routes the per-chunk compute to the Pallas
    flash kernel on TPU when the chunk shapes qualify; the einsum
    online-softmax path remains the fallback (and the CPU test oracle).
    """
    mesh = mesh if mesh is not None else _mesh()
    n = _sep_size(mesh, axis)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if n == 1:
        return _serial_attention(q, k, v, causal, scale)
    if q.shape[1] % n:
        raise ValueError(f"sequence {q.shape[1]} not divisible by sep={n}")
    if use_flash is None:
        from ..ops import dispatch as _dispatch
        from ..ops.pallas import flash_attention as _fa
        q_chunk = jax.ShapeDtypeStruct(
            (q.shape[0], q.shape[1] // n) + q.shape[2:], q.dtype)
        kv_chunk = jax.ShapeDtypeStruct(
            (k.shape[0], k.shape[1] // n) + k.shape[2:], k.dtype)
        # on-chip chunk A/B (tools/ring_chunk_bench.py, BENCH.md §ring):
        # the kernel wins 4-5x at chunk >= 2048 but its fixed costs lose
        # to the einsum online-softmax step below that — long context
        # (the regime ring exists for) is exactly the >= 2048 side
        min_chunk = int(os.environ.get("PDTPU_RING_FLASH_MIN_CHUNK", 2048))
        use_flash = (_dispatch.get("flash_attention") is not None
                     and q.shape[1] // n >= min_chunk
                     and _fa.supported(q_chunk, kv_chunk, kv_chunk,
                                       causal=False))
    inner = _ring_inner_flash if use_flash else _ring_inner
    # With the FLASH inner, the shard_map must be manual over EVERY mesh
    # axis the operands are sharded on — a pallas_call inside a
    # partial-manual region would need auto-partitioning over the
    # remaining axes (batch over dp/sharding, heads over mp), which
    # Mosaic kernels cannot do.  The einsum inner auto-partitions fine
    # and keeps the minimal {sep} manual set.
    manual = {axis}
    bspec = hspec = None
    if use_flash:
        names = set(mesh.axis_names)
        batch_axes = tuple(a for a in ("dp", "sharding")
                           if a in names and mesh.shape[a] > 1)
        bdeg = math.prod(mesh.shape[a] for a in batch_axes) \
            if batch_axes else 1
        mp_ax = "mp" if "mp" in names and mesh.shape["mp"] > 1 else None
        mdeg = mesh.shape[mp_ax] if mp_ax else 1
        if not batch_axes or q.shape[0] % bdeg == 0:
            bspec = batch_axes or None
        else:
            # batch not divisible: fall back to the einsum inner rather
            # than risk a Mosaic auto-partition error
            inner, use_flash = _ring_inner, False
        if use_flash and mp_ax:
            if q.shape[2] % mdeg or k.shape[2] % mdeg:
                inner, use_flash = _ring_inner, False
                bspec = None
            else:
                hspec = mp_ax
        if use_flash:
            # ALL mesh axes go manual (size-1 axes included): any axis
            # left in auto mode keeps the SPMD partitioner responsible
            # for the pallas_call inside, which Mosaic rejects
            manual |= set(mesh.axis_names)
    spec = P(bspec, axis, hspec, None)
    # the ring rank rides in as DATA (arange sharded over the sep axis:
    # each shard sees its own index) instead of lax.axis_index — the
    # axis_index form lowers to a PartitionId op that the TPU SPMD
    # partitioner rejects when the shard_map covers only some mesh axes
    rank_ids = jax.lax.with_sharding_constraint(
        jnp.arange(n, dtype=jnp.int32),
        jax.sharding.NamedSharding(mesh, P(axis)))
    fn = shard_map(
        functools.partial(inner, axis=axis, n=n, causal=causal,
                          scale=float(scale)),
        mesh=mesh, in_specs=(spec, spec, spec, P(axis)), out_specs=spec,
        axis_names=frozenset(manual), check_vma=False)
    return fn(q, k, v, rank_ids)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) attention
# ---------------------------------------------------------------------------

def _ulysses_inner(q, k, v, *, axis, n, causal, scale):
    # local [b, S/n, h, d] → heads scatter / sequence gather → [b, S, h/n, d]
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                            split_axis=2, concat_axis=1, tiled=True)
    q, k, v = a2a(q), a2a(k), a2a(v)
    out = _serial_attention(q, k, v, causal, scale)   # flash kernel on TPU
    return jax.lax.all_to_all(out, axis_name=axis, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, causal=False, scale=None, axis="sep",
                      mesh=None):
    """DeepSpeed-Ulysses attention: sequence shard ↔ head shard all-to-all.

    Requires q heads divisible by the sep degree; kv heads are
    repeat-interleaved to the least multiple of the degree when GQA leaves
    a kv-head count that does not split n ways.
    """
    mesh = mesh if mesh is not None else _mesh()
    n = _sep_size(mesh, axis)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if n == 1:
        return _serial_attention(q, k, v, causal, scale)
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if s % n:
        raise ValueError(f"sequence {s} not divisible by sep={n}")
    if h % n:
        raise ValueError(f"q heads {h} not divisible by sep={n}")
    if hkv % n:
        # repeat-interleave kv heads to the least multiple that splits
        # n ways; block-splitting the repeated heads preserves the GQA
        # q→kv mapping (floor((p·hkv'/h)/rep) == floor(p·hkv/h))
        rep = n // math.gcd(hkv, n)
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ulysses_inner, axis=axis, n=n, causal=causal,
                          scale=float(scale)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis}), check_vma=False)
    return fn(q, k, v)


def context_parallel_attention(q, k, v, causal=False, scale=None,
                               impl="ring", axis="sep", mesh=None):
    """Dispatch by impl name ("ring" | "ulysses"); the model-facing entry."""
    if impl == "ring":
        return ring_attention(q, k, v, causal, scale, axis, mesh)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, causal, scale, axis, mesh)
    raise ValueError(f"unknown context-parallel impl {impl!r} "
                     "(expected 'ring' or 'ulysses')")


def split_sequence(x, axis_idx=1, axis="sep", mesh=None):
    """Constrain a [b, s, ...] activation's sequence dim onto the sep axis
    (the data-layout contract every cp attention above assumes).  A 4-D
    [b, s, heads, d] input keeps its heads on "mp" so cp composes with
    tensor parallelism instead of un-sharding the head dim."""
    mesh = mesh if mesh is not None else _mesh()
    if mesh is None or axis not in mesh.axis_names:
        return x
    from .mp_layers import constrain
    entries = [None] * x.ndim
    entries[axis_idx] = axis
    entries[0] = ("dp", "sharding")
    if x.ndim == 4:
        entries[2] = "mp"
    return constrain(x, *entries)
