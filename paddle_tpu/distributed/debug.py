"""Collective-consistency watchdog (SURVEY §5.2 TPU equivalent).

Reference capability: ProcessGroupNCCL's watchdog thread detects hung /
mismatched collectives by timeout (paddle/fluid/distributed/collective/
process_group_nccl.cc). On TPU the classic deadlock cause survives in
multi-host SPMD: every process must issue the SAME sequence of
collectives; a rank that diverges (data-dependent Python branch, skipped
step, different mesh) hangs the whole slice with no diagnostics.

This module gives the debugging tool the reference has and jax lacks:

- ``collective_debug()``: context manager that records every collective
  issued through ``paddle_tpu.distributed`` (op, axes, shape, dtype) into
  a per-process trace.
- ``check_consistency(...)``: cross-checks the trace digest across
  processes through the rendezvous ``TCPStore`` and raises on the ranks
  whose sequence differs — turning a silent hang into a named error,
  BEFORE the mismatched program is issued again.

Zero overhead when disabled (one falsy global check per collective).
"""

from __future__ import annotations

import hashlib
import threading
from typing import List, Optional, Tuple

__all__ = ["collective_debug", "get_trace", "check_consistency",
           "CollectiveMismatchError"]

_state = threading.local()


class CollectiveMismatchError(RuntimeError):
    pass


def _tracing() -> bool:
    return getattr(_state, "trace", None) is not None


def record(op: str, axes, shape=None, dtype=None) -> None:
    """Called by the communication layer for every collective issued."""
    trace = getattr(_state, "trace", None)
    if trace is None:
        return
    trace.append((op, tuple(axes) if axes else (),
                  tuple(shape) if shape is not None else (),
                  str(dtype) if dtype is not None else ""))


class collective_debug:
    """``with collective_debug() as trace:`` — record collective calls."""

    def __enter__(self) -> List[Tuple]:
        _state.trace = []
        return _state.trace

    def __exit__(self, *exc):
        self._trace = _state.trace
        _state.trace = None
        return False


def get_trace() -> Optional[List[Tuple]]:
    return getattr(_state, "trace", None)


def _digest(trace) -> str:
    h = hashlib.sha256()
    for entry in trace:
        h.update(repr(entry).encode())
    return h.hexdigest()


def check_consistency(trace, rank: int, world_size: int, store=None,
                      master_endpoint: Optional[str] = None,
                      timeout: float = 30.0) -> None:
    """Raise ``CollectiveMismatchError`` on ranks whose collective
    sequence differs from rank 0's.

    Exchange rides the rendezvous TCPStore (control plane — never the
    accelerator fabric, which may be the thing that's wedged).
    """
    if world_size <= 1:
        return
    if store is None:
        from ..launch.store import TCPStore
        store = TCPStore(master_endpoint, is_master=(rank == 0),
                         timeout=timeout)
    d = _digest(trace)
    store.set(f"collective_watchdog/{rank}", d.encode())
    # everyone compares against rank 0 (wait gives the natural timeout)
    ref = store.wait("collective_watchdog/0", timeout=timeout)
    ref = ref.decode() if isinstance(ref, bytes) else ref
    if d != ref:
        raise CollectiveMismatchError(
            f"rank {rank} issued a different collective sequence than "
            f"rank 0 ({len(trace)} calls, digest {d[:12]} != {ref[:12]}). "
            "First differing call can be found by diffing get_trace() "
            "dumps; typical causes: data-dependent branch around a "
            "collective, unequal dataset shards, mesh mismatch.")
