"""Parameter-server tables: dense + sparse, host-resident.

Reference: paddle/fluid/distributed/ps/table/ — ``Table`` hierarchy
(``MemoryDenseTable``, ``MemorySparseTable``) with pluggable accessors
(sparse SGD/AdaGrad/Adam rules), geo-async delta tracking
(SURVEY §2.5 "Parameter server" row).

TPU redesign: tables are host-RAM numpy state (the reference keeps them in
server CPU memory too — this part of Paddle never touched the GPU except
via heter-PS caching). Device compute stays dense jax; the PS exists so
embedding tables far larger than HBM can live on host/parameter servers
while pulled working-sets ride to the TPU as ordinary dense inputs. No
kernel work belongs here, so numpy (not jnp) is deliberate: rows are
mutated in place, which XLA arrays cannot do.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

__all__ = ["SparseAccessor", "DenseTable", "SparseTable"]


class SparseAccessor:
    """Per-row update rule (reference: sparse accessor configs naming
    ``sgd``/``adagrad``/``adam`` in table proto)."""

    RULES = ("sgd", "adagrad", "adam")

    def __init__(self, rule: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8):
        if rule not in self.RULES:
            raise ValueError(f"unknown accessor rule {rule!r}; one of {self.RULES}")
        self.rule = rule
        self.lr = float(lr)
        self.beta1, self.beta2, self.eps = float(beta1), float(beta2), float(eps)

    def slot_count(self) -> int:
        return {"sgd": 0, "adagrad": 1, "adam": 2}[self.rule]

    def apply(self, param: np.ndarray, grad: np.ndarray,
              slots: Optional[np.ndarray], step: int) -> None:
        """In-place update of ``param`` (and ``slots``) given ``grad``."""
        if self.rule == "sgd":
            param -= self.lr * grad
        elif self.rule == "adagrad":
            g2 = slots[0]
            g2 += grad * grad
            param -= self.lr * grad / (np.sqrt(g2) + self.eps)
        else:  # adam
            m, v = slots[0], slots[1]
            m *= self.beta1
            m += (1 - self.beta1) * grad
            v *= self.beta2
            v += (1 - self.beta2) * grad * grad
            mhat = m / (1 - self.beta1 ** step)
            vhat = v / (1 - self.beta2 ** step)
            param -= self.lr * mhat / (np.sqrt(vhat) + self.eps)


class DenseTable:
    """Replicated dense parameter block (reference: MemoryDenseTable —
    summed worker grads applied server-side)."""

    def __init__(self, name: str, shape, accessor: Optional[SparseAccessor] = None,
                 initializer=None, seed: int = 0):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.accessor = accessor or SparseAccessor("sgd", lr=0.01)
        rng = np.random.default_rng(seed)
        if initializer is None:
            self.param = np.zeros(self.shape, np.float32)
        else:
            self.param = np.asarray(initializer(rng, self.shape), np.float32)
        k = self.accessor.slot_count()
        self.slots = np.zeros((k,) + self.shape, np.float32) if k else None
        self.step = 0
        self.lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self.lock:
            return self.param.copy()

    def push(self, grad: np.ndarray) -> None:
        grad = np.asarray(grad, np.float32)
        if grad.shape != self.shape:
            raise ValueError(f"dense push shape {grad.shape} != {self.shape}")
        with self.lock:
            self.step += 1
            self.accessor.apply(self.param, grad, self.slots, self.step)

    def set(self, value: np.ndarray) -> None:
        with self.lock:
            self.param[...] = np.asarray(value, np.float32)

    def state_dict(self):
        with self.lock:
            return {"param": self.param.copy(),
                    "slots": None if self.slots is None else self.slots.copy(),
                    "step": self.step}

    def load_state_dict(self, state):
        with self.lock:
            self.param[...] = state["param"]
            if self.slots is not None and state.get("slots") is not None:
                self.slots[...] = state["slots"]
            self.step = int(state.get("step", 0))


class SparseTable:
    """Hash-keyed embedding rows, lazily created on first pull
    (reference: MemorySparseTable shards rows over servers; lazy init with
    the table's initializer; geo-SGD keeps per-key deltas).

    Thread-safe; rows are float32 ``dim``-vectors keyed by int64 ids.
    """

    def __init__(self, name: str, dim: int, accessor: Optional[SparseAccessor] = None,
                 initializer=None, seed: int = 0):
        self.name = name
        self.dim = int(dim)
        self.accessor = accessor or SparseAccessor("sgd", lr=0.01)
        self._init = initializer
        self._seed = int(seed)
        self.rows: Dict[int, np.ndarray] = {}
        self.slots: Dict[int, np.ndarray] = {}
        self.steps: Dict[int, int] = {}
        self.lock = threading.Lock()
        # geo-async: per-key accumulated parameter deltas since last fetch
        self._geo_base: Dict[int, np.ndarray] = {}

    def _new_row(self, key: int) -> np.ndarray:
        if self._init is None:
            return np.zeros(self.dim, np.float32)
        # deterministic per-key init so every server/replica agrees
        rng = np.random.default_rng((self._seed * 0x9E3779B9 + key) & 0xFFFFFFFF)
        return np.asarray(self._init(rng, (self.dim,)), np.float32)

    def pull(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        out = np.empty((keys.size, self.dim), np.float32)
        with self.lock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self._new_row(k)
                    self.rows[k] = row
                out[i] = row
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray,
             geo_track: bool = False) -> None:
        keys = np.asarray(keys, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(keys.size, self.dim)
        k_slots = self.accessor.slot_count()
        with self.lock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self._new_row(k)
                    self.rows[k] = row
                if k_slots and k not in self.slots:
                    self.slots[k] = np.zeros((k_slots, self.dim), np.float32)
                if geo_track and k not in self._geo_base:
                    self._geo_base[k] = row.copy()
                self.steps[k] = self.steps.get(k, 0) + 1
                self.accessor.apply(row, grads[i],
                                    self.slots.get(k), self.steps[k])

    def push_delta(self, keys: np.ndarray, deltas: np.ndarray) -> None:
        """Geo-async merge: add raw parameter deltas (reference geo-SGD:
        servers sum worker deltas rather than applying grads)."""
        keys = np.asarray(keys, np.int64).ravel()
        deltas = np.asarray(deltas, np.float32).reshape(keys.size, self.dim)
        with self.lock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self._new_row(k)
                    self.rows[k] = row
                row += deltas[i]

    def pop_geo_deltas(self):
        """Return and clear (keys, deltas) accumulated by geo-tracked
        pushes — what a geo worker sends upstream."""
        with self.lock:
            keys = np.fromiter(self._geo_base.keys(), np.int64,
                               len(self._geo_base))
            deltas = np.stack([self.rows[k] - self._geo_base[k]
                               for k in keys.tolist()]) if keys.size else \
                np.zeros((0, self.dim), np.float32)
            self._geo_base.clear()
        return keys, deltas

    def __len__(self):
        with self.lock:
            return len(self.rows)

    def state_dict(self):
        with self.lock:
            keys = np.fromiter(self.rows.keys(), np.int64, len(self.rows))
            klist = keys.tolist()
            vals = (np.stack([self.rows[k] for k in klist])
                    if keys.size else np.zeros((0, self.dim), np.float32))
            n_slots = self.accessor.slot_count()
            slots = (np.stack([self.slots.get(
                k, np.zeros((n_slots, self.dim), np.float32)) for k in klist])
                if keys.size and n_slots else None)
            steps = np.asarray([self.steps.get(k, 0) for k in klist], np.int64)
            return {"keys": keys, "values": vals, "slots": slots,
                    "steps": steps}

    def load_state_dict(self, state):
        with self.lock:
            self.rows = {int(k): np.asarray(v, np.float32).copy()
                         for k, v in zip(state["keys"], state["values"])}
            # stale accumulators from prior contents must not leak onto
            # freshly loaded rows
            self.slots, self.steps, self._geo_base = {}, {}, {}
            if state.get("slots") is not None:
                for k, s in zip(state["keys"], state["slots"]):
                    self.slots[int(k)] = np.asarray(s, np.float32).copy()
            for k, st in zip(state["keys"], state.get("steps", ())):
                self.steps[int(k)] = int(st)
