"""Parameter-server mode (``paddle.distributed.ps`` / fleet PS parity).

Reference: paddle/fluid/distributed/ps/ (BrpcPsServer/Client, Table
hierarchy, geo-async SGD), python/paddle/distributed/fleet — the
non-collective role flow: ``PaddleCloudRoleMaker`` → ``fleet.init(role)``
→ servers ``init_server()/run_server()``, trainers ``init_worker()`` …
``stop_worker()`` (SURVEY §2.5 "Parameter server", §3.5 env protocol).

TPU redesign: the PS exists for sparse state larger than HBM
(recommendation embeddings). Servers are plain CPU processes hosting
numpy tables behind the framework's control-plane RPC; trainers pull a
batch's working-set of rows (host-side), run the *dense* compute on the
TPU as one jitted step, then push row gradients back. Geo-async mirrors
the reference's geo-SGD: trainers update a local replica and ship
parameter deltas every ``geo_step`` steps. brpc/heter-PS's GPU-cache has
no TPU analogue worth building — the pull/compute/push split already puts
the dense math on the accelerator.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseAccessor, SparseTable
from .service import PsClient, PsService, TableConfig, _install_service, _svc_call

__all__ = [
    "DenseTable", "SparseTable", "SparseAccessor", "TableConfig",
    "PsService", "PsClient", "PaddleCloudRoleMaker", "PsRuntime",
    "DistributedEmbedding", "GeoWorkerTable",
]


class PaddleCloudRoleMaker:
    """Role/topology from the reference's env protocol
    (``PADDLE_TRAINING_ROLE``, ``PADDLE_PSERVERS_IP_PORT_LIST``,
    ``PADDLE_TRAINERS_NUM``, ``PADDLE_TRAINER_ID``, ``POD_IP``,
    ``PADDLE_PORT``) — reference: fleet/base/role_maker.py [SURVEY §3.2]."""

    def __init__(self, is_collective: bool = False, env: Optional[dict] = None):
        e = os.environ if env is None else env
        self.is_collective = is_collective
        role = e.get("PADDLE_TRAINING_ROLE", "TRAINER").upper()
        self._is_server = role == "PSERVER"
        self.server_endpoints: List[str] = [
            p for p in e.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if p]
        self.trainer_num = int(e.get("PADDLE_TRAINERS_NUM", "1"))
        self.trainer_id = int(e.get("PADDLE_TRAINER_ID", "0"))
        if self._is_server:
            me = f"{e.get('POD_IP', '127.0.0.1')}:{e.get('PADDLE_PORT', '0')}"
            self.server_id = (self.server_endpoints.index(me)
                              if me in self.server_endpoints else 0)
        else:
            self.server_id = -1

    def is_server(self) -> bool:
        return self._is_server

    def is_worker(self) -> bool:
        return not self._is_server

    def worker_index(self) -> int:
        return self.trainer_id

    def worker_num(self) -> int:
        return self.trainer_num

    def server_num(self) -> int:
        return len(self.server_endpoints) or 1


class PsRuntime:
    """Orchestrates one PS job. Two transports:

    - ``local``: every server lives in-process (tests, single-host) —
      ``PsRuntime.local(configs, num_servers)``.
    - rpc: each process calls ``init_server()/run_server()`` or
      ``init_worker()`` per its role, discovery rides the rpc name table
      (servers register as ``ps0..psN-1``).
    """

    def __init__(self, role: PaddleCloudRoleMaker,
                 configs: Sequence[TableConfig],
                 master_endpoint: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: int = 0):
        self.role = role
        self.configs = list(configs)
        self.master_endpoint = master_endpoint
        # server-side fault tolerance (reference: PS table snapshots,
        # SURVEY §5.3): PDTPU_PS_SNAPSHOT_DIR / _EVERY mirror the args so
        # launch scripts can turn it on without code changes
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "PDTPU_PS_SNAPSHOT_DIR") or None
        self.snapshot_every = int(snapshot_every or os.environ.get(
            "PDTPU_PS_SNAPSHOT_EVERY", "0"))
        self.client: Optional[PsClient] = None
        self._service: Optional[PsService] = None
        self._stop = threading.Event()

    # ---- local transport --------------------------------------------
    @classmethod
    def local(cls, configs: Sequence[TableConfig], num_servers: int = 1):
        rt = cls(PaddleCloudRoleMaker(env={}), configs)
        rt.client = PsClient([PsService(configs, i) for i in range(num_servers)])
        return rt

    # ---- rpc transport ----------------------------------------------
    def _world(self) -> int:
        return self.role.server_num() + self.role.worker_num()

    def _rpc_init(self, name: str, rank: int):
        from .. import rpc
        rpc.init_rpc(name, rank=rank, world_size=self._world(),
                     master_endpoint=self.master_endpoint)

    def init_server(self, dirname: Optional[str] = None) -> None:
        """``dirname`` warm-starts from that snapshot dir (reference:
        fleet.init_server(dirname) loads a saved model)."""
        from . import service as _service_mod
        self._service = PsService(self.configs, self.role.server_id,
                                  snapshot_dir=dirname or self.snapshot_dir,
                                  snapshot_every=self.snapshot_every)
        _install_service(self._service)
        _service_mod._RUNTIME_STOP = self._stop
        self._rpc_init(f"ps{self.role.server_id}", self.role.server_id)

    def run_server(self) -> None:
        """Serve until a trainer's stop_worker (or local shutdown)
        releases us (reference: run_server blocks until stop_server)."""
        if self._service is None:
            self.init_server()
        self._stop.wait()
        from .. import rpc
        rpc.shutdown()

    def init_worker(self) -> None:
        rank = self.role.server_num() + self.role.worker_index()
        self._rpc_init(f"trainer{self.role.worker_index()}", rank)
        self.client = PsClient([f"ps{i}" for i in range(self.role.server_num())])

    def stop_worker(self) -> None:
        """Reference flow: trainer 0's stop also releases the servers."""
        from .. import rpc
        from .service import _stop_service
        if self.role.worker_index() == 0 and self.client is not None \
                and not self.client.local:
            for name in self.client.servers:
                try:
                    rpc.rpc_sync(name, _stop_service)
                except Exception:
                    pass  # server already gone
        rpc.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        from .. import rpc
        rpc.shutdown()


class GeoWorkerTable:
    """Geo-async trainer-side replica of one sparse table (reference
    geo-SGD: train on a local copy, ship parameter deltas every
    ``geo_step`` pushes, absorb the server's merged state on pull)."""

    def __init__(self, client: PsClient, name: str, dim: int,
                 accessor: Optional[SparseAccessor] = None,
                 geo_step: int = 8, initializer=None, seed: int = 0):
        self.client, self.name, self.geo_step = client, name, int(geo_step)
        self.local = SparseTable(name, dim, accessor, initializer, seed)
        self._pushes = 0

    def pull(self, keys) -> np.ndarray:
        """Sync with the server's merged view: local row becomes
        server_row + (pending unsent local delta). Other workers'
        contributions are thus absorbed on every pull while in-flight
        local progress is preserved (reference geo-SGD pull path)."""
        keys = np.asarray(keys, np.int64).ravel()
        uniq = np.fromiter(dict.fromkeys(keys.tolist()), np.int64)
        rows = self.client.pull_sparse(self.name, uniq)
        with self.local.lock:
            for k, server_row in zip(uniq.tolist(), rows):
                local = self.local.rows.get(k)
                base = self.local._geo_base.get(k)
                pending = (local - base) if (local is not None
                                             and base is not None) else 0.0
                merged = server_row + pending
                self.local.rows[k] = merged
                if base is not None:
                    self.local._geo_base[k] = server_row.copy()
        return self.local.pull(keys)

    def push(self, keys, grads) -> None:
        self.local.push(keys, grads, geo_track=True)
        self._pushes += 1
        if self._pushes % self.geo_step == 0:
            dk, dv = self.local.pop_geo_deltas()
            if dk.size:
                self.client.push_sparse_delta(self.name, dk, dv)


class DistributedEmbedding:
    """Sparse-embedding front half of a PS model
    (reference: ``paddle.static.nn.sparse_embedding`` /
    ``fleet.embedding`` routed to pull_sparse/push_sparse).

    TPU usage pattern: ``pull(ids)`` host-side (input pipeline), feed the
    dense rows into the jitted step as an ordinary array, take
    ``d_rows`` out of the step's grads, then ``push(ids, d_rows)``.
    Duplicate ids within a batch are pulled once and their gradients
    summed before pushing, matching dense-embedding autograd semantics.
    """

    def __init__(self, client_or_runtime, name: str, dim: int):
        rt = client_or_runtime
        self.client = rt.client if isinstance(rt, PsRuntime) else rt
        if self.client is None:
            raise RuntimeError("runtime has no client (server role?)")
        self.name, self.dim = name, int(dim)

    def pull(self, ids):
        """→ (unique_rows [n,dim] float32, inverse [ids.shape] int32):
        ``rows[inverse]`` reconstructs per-position embeddings on device."""
        ids = np.asarray(ids, np.int64)
        uniq, inverse = np.unique(ids, return_inverse=True)
        rows = self.client.pull_sparse(self.name, uniq)
        self._last = (uniq, ids.shape)
        return rows, inverse.reshape(ids.shape).astype(np.int32)

    def push(self, d_rows) -> None:
        """Push gradients w.r.t. the unique rows of the last pull."""
        uniq, _ = self._last
        self.client.push_sparse(self.name, uniq,
                                np.asarray(d_rows, np.float32))

    def push_rows(self, rows_grad) -> None:
        """Push a device-side ``sparse.RowsGrad`` (SelectedRows) keyed by
        raw vocabulary ids — the per-lookup gradient straight out of the
        jitted step, no pull bookkeeping needed.  Drop-slot rows (id >=
        vocab, from padding or coalesce parking) are filtered host-side."""
        rows = np.asarray(rows_grad.rows, np.int64)
        vals = np.asarray(rows_grad.values, np.float32)
        keep = rows < rows_grad.dense_shape[0]
        rows, vals = rows[keep], vals[keep]
        if not rows.size:
            return
        # host-side coalesce: duplicate lookups must reach the table as ONE
        # summed update (SelectedRows merge semantics) — per-duplicate
        # accessor.apply calls would bump adaptive-rule steps per lookup
        uniq, inv = np.unique(rows, return_inverse=True)
        summed = np.zeros((uniq.size, vals.shape[1]), np.float32)
        np.add.at(summed, inv, vals)
        self.client.push_sparse(self.name, uniq, summed)
