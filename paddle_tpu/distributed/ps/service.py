"""PS service + client: key-sharded push/pull across servers.

Reference: paddle/fluid/distributed/ps/service/ — ``BrpcPsServer`` /
``BrpcPsClient`` (push_dense/pull_dense/push_sparse/pull_sparse RPCs,
rows sharded over servers by key hash), SURVEY §2.5.

TPU redesign: brpc → the framework's own control-plane RPC
(``paddle_tpu.distributed.rpc``); one ``PsService`` object per server
process hosts the tables, trainers talk through ``PsClient`` which shards
keys by ``key % num_servers`` (the reference's default hash) and merges
results. A ``local`` transport (direct object calls) serves single-process
mode and tests; the wire transport rides rpc_sync to named ps workers.
"""

from __future__ import annotations

import concurrent.futures as cf
import json
import os
import threading
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseAccessor, SparseTable

__all__ = ["TableConfig", "PsService", "PsClient"]


class TableConfig:
    """Declarative table spec shared by every server and client
    (reference: the ps table proto in DistributedStrategy)."""

    def __init__(self, name: str, kind: str = "sparse", dim: int = 8,
                 shape=None, rule: str = "sgd", lr: float = 0.01,
                 initializer=None, seed: int = 0, **accessor_kw):
        if kind not in ("sparse", "dense"):
            raise ValueError("kind must be 'sparse' or 'dense'")
        self.name, self.kind, self.dim = name, kind, int(dim)
        self.shape = tuple(shape) if shape is not None else None
        self.rule, self.lr = rule, float(lr)
        self.initializer, self.seed = initializer, int(seed)
        self.accessor_kw = accessor_kw

    def build(self):
        acc = SparseAccessor(self.rule, lr=self.lr, **self.accessor_kw)
        if self.kind == "dense":
            if self.shape is None:
                raise ValueError(f"dense table {self.name!r} needs shape=")
            return DenseTable(self.name, self.shape, acc,
                              self.initializer, self.seed)
        return SparseTable(self.name, self.dim, acc,
                           self.initializer, self.seed)


class PsService:
    """Server-side table host. Methods are the RPC surface.

    Fault tolerance (reference: the PS table snapshot path —
    fleet.save_one_table / server-side checkpointing, SURVEY §5.3 "PS
    mode has server-side fault tolerance"): with ``snapshot_dir`` set the
    server persists every table every ``snapshot_every`` pushes (atomic
    tmp+rename npz per table, manifest written last) and a RESTARTED
    server with the same dir resumes from the latest snapshot — a killed
    table server loses at most the pushes since the last snapshot."""

    def __init__(self, configs: Sequence[TableConfig], server_rank: int = 0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: int = 0):
        self.server_rank = server_rank
        self.tables: Dict[str, object] = {c.name: c.build() for c in configs}
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self._push_count = 0
        self._snap_lock = threading.Lock()
        if snapshot_dir:
            self.load_snapshot()   # warm-start if a snapshot exists

    # ---- snapshot / restore ------------------------------------------
    def _snap_path(self, dirname=None) -> str:
        d = dirname or self.snapshot_dir
        if not d:
            raise ValueError("no snapshot_dir configured")
        return os.path.join(d, f"server{self.server_rank}")

    def save_snapshot(self, dirname: Optional[str] = None) -> str:
        """Atomically persist every table; returns the snapshot dir."""
        root = self._snap_path(dirname)
        os.makedirs(root, exist_ok=True)
        with self._snap_lock:
            names = []
            for name, table in self.tables.items():
                state = table.state_dict()
                arrays = {k: v for k, v in state.items()
                          if isinstance(v, np.ndarray)}
                scalars = {k: v for k, v in state.items()
                           if not isinstance(v, np.ndarray) and v is not None}
                tmp = os.path.join(root, f"{name}.npz.tmp")
                with open(tmp, "wb") as f:
                    np.savez(f, __scalars__=json.dumps(scalars), **arrays)
                os.replace(tmp, os.path.join(root, f"{name}.npz"))
                names.append(name)
            manifest = {"tables": names, "push_count": self._push_count,
                        "server_rank": self.server_rank}
            tmp = os.path.join(root, "manifest.json.tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, os.path.join(root, "manifest.json"))
        return root

    def load_snapshot(self, dirname: Optional[str] = None) -> bool:
        """Restore from the latest snapshot; False if none exists."""
        root = self._snap_path(dirname)
        mpath = os.path.join(root, "manifest.json")
        if not os.path.exists(mpath):
            return False
        with open(mpath) as f:
            manifest = json.load(f)
        for name in manifest["tables"]:
            if name not in self.tables:
                continue   # config changed since the snapshot
            with np.load(os.path.join(root, f"{name}.npz"),
                         allow_pickle=False) as z:
                state = {k: z[k] for k in z.files if k != "__scalars__"}
                if "__scalars__" in z.files:
                    state.update(json.loads(str(z["__scalars__"])))
            # savez stores None-valued entries as absent: normalize
            state.setdefault("slots", None)
            self.tables[name].load_state_dict(state)
        self._push_count = int(manifest.get("push_count", 0))
        return True

    def _maybe_snapshot(self) -> None:
        self._push_count += 1
        if (self.snapshot_dir and self.snapshot_every
                and self._push_count % self.snapshot_every == 0):
            self.save_snapshot()

    def _sparse(self, name) -> SparseTable:
        t = self.tables[name]
        if not isinstance(t, SparseTable):
            raise TypeError(f"table {name!r} is not sparse")
        return t

    def _dense(self, name) -> DenseTable:
        t = self.tables[name]
        if not isinstance(t, DenseTable):
            raise TypeError(f"table {name!r} is not dense")
        return t

    # ---- RPC surface -------------------------------------------------
    def pull_dense(self, name):
        return self._dense(name).pull()

    def push_dense(self, name, grad):
        self._dense(name).push(grad)
        self._maybe_snapshot()

    def pull_sparse(self, name, keys):
        return self._sparse(name).pull(keys)

    def push_sparse(self, name, keys, grads):
        self._sparse(name).push(keys, grads)
        self._maybe_snapshot()

    def push_sparse_delta(self, name, keys, deltas):
        self._sparse(name).push_delta(keys, deltas)
        self._maybe_snapshot()

    def state_dict(self):
        return {n: t.state_dict() for n, t in self.tables.items()}

    def load_state_dict(self, state):
        for n, s in state.items():
            self.tables[n].load_state_dict(s)


# module-level dispatcher so the rpc layer (pickle-by-name callables) can
# reach the per-process service instance
_SERVICE: Optional[PsService] = None


def _install_service(svc: PsService) -> None:
    global _SERVICE
    _SERVICE = svc


def _svc_call(method: str, *args):
    if _SERVICE is None:
        raise RuntimeError("no PsService running in this process "
                           "(call fleet.init_server / run_server first)")
    return getattr(_SERVICE, method)(*args)


# set by PsRuntime.init_server so a trainer's stop request (rpc'd to this
# process) can release run_server()'s wait
_RUNTIME_STOP = None


def _stop_service():
    if _RUNTIME_STOP is not None:
        _RUNTIME_STOP.set()


class PsClient:
    """Trainer-side handle. ``servers`` is either a list of ``PsService``
    objects (local transport) or a list of rpc worker names (wire
    transport over ``paddle_tpu.distributed.rpc``)."""

    def __init__(self, servers: Sequence):
        if not servers:
            raise ValueError("need at least one server")
        self.servers = list(servers)
        self.local = isinstance(self.servers[0], PsService)
        # wire transport: fan shard requests out concurrently (reference:
        # brpc client issues per-shard requests in parallel)
        self._pool = None if self.local else cf.ThreadPoolExecutor(
            max_workers=min(16, len(self.servers)),
            thread_name_prefix="pdtpu-ps")

    def _call(self, idx: int, method: str, *args):
        if self.local:
            return getattr(self.servers[idx], method)(*args)
        from .. import rpc
        return rpc.rpc_sync(self.servers[idx], _svc_call, args=(method,) + args)

    def _scatter_calls(self, calls):
        """[(server_idx, method, args)] → results, concurrently when remote."""
        if self._pool is None:
            return [self._call(i, m, *a) for i, m, a in calls]
        futs = [self._pool.submit(self._call, i, m, *a) for i, m, a in calls]
        return [f.result() for f in futs]

    # dense tables are hosted on one server picked by stable name hash
    # (process-salted builtin hash would fork the table across processes)
    def _dense_home(self, name: str) -> int:
        return zlib.crc32(name.encode()) % len(self.servers)

    def pull_dense(self, name: str) -> np.ndarray:
        return self._call(self._dense_home(name), "pull_dense", name)

    def push_dense(self, name: str, grad) -> None:
        self._call(self._dense_home(name), "push_dense", name,
                   np.asarray(grad, np.float32))

    def _shard(self, keys):
        keys = np.asarray(keys, np.int64).ravel()
        owner = keys % len(self.servers)
        return keys, owner

    def pull_sparse(self, name: str, keys) -> np.ndarray:
        keys, owner = self._shard(keys)
        shards = [(s, np.nonzero(owner == s)[0])
                  for s in range(len(self.servers))]
        shards = [(s, idx) for s, idx in shards if idx.size]
        if not shards:  # zero keys
            dim = self._call(0, "pull_sparse", name,
                             np.zeros(0, np.int64)).shape[-1]
            return np.zeros((0, dim), np.float32)
        results = self._scatter_calls(
            [(s, "pull_sparse", (name, keys[idx])) for s, idx in shards])
        out = np.empty((keys.size, results[0].shape[1]), np.float32)
        for (s, idx), rows in zip(shards, results):
            out[idx] = rows
        return out

    def push_sparse(self, name: str, keys, grads) -> None:
        self._push(name, keys, grads, "push_sparse")

    def push_sparse_delta(self, name: str, keys, deltas) -> None:
        """Geo-async upstream merge (reference geo-SGD)."""
        self._push(name, keys, deltas, "push_sparse_delta")

    def _push(self, name, keys, values, method):
        keys, owner = self._shard(keys)
        values = np.asarray(values, np.float32).reshape(keys.size, -1)
        calls = []
        for s in range(len(self.servers)):
            idx = np.nonzero(owner == s)[0]
            if idx.size:
                calls.append((s, method, (name, keys[idx], values[idx])))
        self._scatter_calls(calls)
