"""Auto-parallel static engine (reference:
python/paddle/distributed/auto_parallel/static/engine.py ``Engine`` and
api.py ``to_static``/``DistModel``).

In the reference, Engine captures the dygraph model into a distributed
static Program, runs the planner/partitioner over the cluster topology,
and executes with a fleet executor.  The TPU-native pipeline is shorter by
construction: parameters carry placements (mesh axes in ``param_meta``),
``jit.TrainStep`` compiles ONE SPMD program with those shardings, and XLA
is the planner/partitioner.  The Engine here is therefore a thin,
reference-shaped driver: mode management (train/eval/predict), dataloader
sharding, and a compiled step per mode.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..observability import _state as _obs_state
from ..observability.spans import span as _span
from . import fleet
from .auto import _to_jax_mesh, shard_dataloader

__all__ = ["Engine", "to_static", "DistModel"]


class Engine:
    """Reference-shaped auto-parallel driver over ``jit.TrainStep``.

    Usage::

        engine = dist.Engine(model, loss_fn, optimizer, mesh=mesh)
        engine.fit(train_loader, epochs=2)
        metrics = engine.evaluate(val_loader)
        preds = engine.predict(test_loader)
    """

    def __init__(self, model: Layer, loss: Optional[Callable] = None,
                 optimizer=None, metrics=None, strategy=None,
                 mesh=None, scaler=None):
        from ..jit import TrainStep
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy
        self._step = None
        self._state = None
        if loss is not None and optimizer is not None:
            # TrainStep owns the fleet-mesh fallback; loss already has the
            # (model, batch) shape it expects
            self._step = TrainStep(model, loss, optimizer, scaler=scaler,
                                   mesh=_to_jax_mesh(mesh)
                                   if mesh is not None else None)
            self.mesh = self._step.mesh
        elif mesh is not None:
            self.mesh = _to_jax_mesh(mesh)
        else:
            hcg = fleet.get_hybrid_communicate_group()
            self.mesh = hcg.mesh if hcg is not None else None
        self._eval_fn = None
        self._predict_fn = None

    # -- state -------------------------------------------------------------

    @property
    def state(self):
        if self._state is None:
            if self._step is None:
                raise RuntimeError(
                    "Engine has no training step: pass loss and optimizer")
            self._state = self._step.init_state()
        return self._state

    def _loader(self, data, shard=True):
        if data is None:
            return ()
        if self.mesh is not None and shard and not hasattr(data, "_mesh"):
            if self._step is not None:
                # reuse the step's own batch axes so loader sharding and
                # the step's sharding constraint can never disagree
                entry = self._step.batch_spec[0] \
                    if len(self._step.batch_spec) else None
                axes = list(entry) if isinstance(entry, tuple) \
                    else [entry] if entry else []
            else:
                axes = [a for a in ("dp", "sharding") if a in
                        self.mesh.axis_names and self.mesh.shape[a] > 1]
            if axes:
                return shard_dataloader(data, self.mesh, shard_dims=axes)
        return data

    # -- modes -------------------------------------------------------------

    def fit(self, train_data, epochs: int = 1, valid_data=None,
            log_freq: int = 10, callback: Optional[Callable] = None):
        """Train over the (auto-sharded) loader; returns last metrics."""
        metrics = {}
        if epochs > 1 and iter(train_data) is train_data:
            raise TypeError(
                "fit(epochs>1) needs a re-iterable loader/dataset, not a "
                "one-shot iterator — epochs after the first would silently "
                "run zero steps")
        for epoch in range(epochs):
            loader = self._loader(train_data)
            i = -1
            # epoch span: duration histogram + a chrome-trace slot in the
            # same vocabulary as the per-step events
            with _span("Engine.fit.epoch",
                       site=getattr(self._step, "_site", None),
                       epoch=epoch):
                for i, batch in enumerate(loader):
                    # the step donates the state buffers: keep self._state
                    # pointing at the LIVE pytree so mid-fit evaluate()
                    # (and a user interrupt) never reads donated arrays.
                    # Per-step telemetry (wall time, tokens/sec, MFU) is
                    # emitted by TrainStep.__call__ itself when
                    # observability is enabled.
                    self._state, metrics = self._step(self.state, batch)
                    if callback is not None and i % log_freq == 0:
                        callback(epoch, i, {k: float(v)
                                            for k, v in metrics.items()})
            if valid_data is not None:
                metrics["eval_loss"] = self.evaluate(valid_data)["loss"]
            emit = _obs_state.EMIT[0]
            if emit is not None:
                emit({"event": "epoch", "site": self._step._site,
                      "epoch": epoch, "steps": i + 1,
                      **{k: float(v) for k, v in metrics.items()}})
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self, valid_data):
        """Mean loss over the loader with the CURRENT trained params."""
        if self.loss is None:
            raise RuntimeError("Engine needs a loss for evaluate()")
        from ..nn.layer import _swapped_params, _train_mode

        if self._eval_fn is None:
            def eval_one(params, batch):
                with _swapped_params(self.model, params), \
                        _train_mode(self.model, False):
                    return self.loss(self.model, batch)
            self._eval_fn = jax.jit(eval_one)
        params = (self.state["params"] if self._step is not None
                  else None)
        from ..nn.layer import raw_params
        if params is None:
            params = raw_params(self.model)
        total, n = 0.0, 0
        for batch in self._loader(valid_data):
            total += float(self._eval_fn(params, batch))
            n += 1
        if n == 0:
            raise ValueError(
                "evaluate(): the loader yielded no batches — a silent 0.0 "
                "here would read as a perfect score")
        return {"loss": total / n}

    def predict(self, test_data, input_keys=None):
        """Forward-only over the loader; list of per-batch outputs.

        ``input_keys``: which dict-batch entries feed the model (the
        reference's feed list); default drops the common label keys."""
        from ..nn.layer import _swapped_params, _train_mode, raw_params

        keys = tuple(input_keys) if input_keys is not None else None
        fns = self.__dict__.setdefault("_predict_fns", {})
        if keys not in fns:   # memoized PER feed list, not just once
            def predict_one(params, batch, keys=keys):
                with _swapped_params(self.model, params), \
                        _train_mode(self.model, False):
                    if isinstance(batch, dict):
                        # by keyword: order-safe against dict insertion
                        feats = {k: v for k, v in batch.items()
                                 if (k in keys if keys is not None
                                     else k not in ("labels", "label",
                                                    "y"))}
                        return self.model(**feats)
                    return self.model(batch)
            fns[keys] = jax.jit(predict_one)
        self._predict_fn = fns[keys]
        params = (self.state["params"] if self._step is not None
                  else raw_params(self.model))
        return [self._predict_fn(params, b) for b in self._loader(test_data)]

    # -- reference surface sugar ------------------------------------------

    def prepare(self, *a, **k):  # reference: mode pre-build; lazy here
        return self

    def cost(self, *a, **k):
        raise NotImplementedError(
            "cost estimation is XLA's job on TPU: compile with "
            "jit(...).lower().compile() and read cost_analysis()")

    def save(self, path: str):
        """Full resumable state — params AND optimizer slots/step/rng (the
        reference Engine checkpoints optimizer state too; dropping it would
        silently replay LR warmup and zero the moments on resume)."""
        from .. import ckpt
        if self._step is None:
            from ..nn.layer import raw_params
            ckpt.save({"params": raw_params(self.model)}, path)
            return
        st = dict(self.state)
        st["rng"] = jax.random.key_data(st["rng"])
        ckpt.save(st, path)

    def load(self, path: str):
        from .. import ckpt
        st = dict(ckpt.load(path))
        if self._step is None:
            # inference-only engine: push params into the live model
            params = st.get("params", st)
            for name, v in dict(params).items():
                self.model._assign_by_path(name, jnp.asarray(v))
            return
        if "rng" in st:
            st["rng"] = jax.random.wrap_key_data(jnp.asarray(st["rng"]))
        full = self._step.shard_state(st)
        self._state = full


class DistModel:
    """Reference: the object ``dist.to_static`` returns — call it per batch
    to run one compiled training step (train mode) or a forward (eval)."""

    def __init__(self, engine: Engine):
        self._engine = engine
        self._mode = "train"

    def train(self):
        self._mode = "train"
        return self

    def eval(self):
        self._mode = "eval"
        return self

    def __call__(self, batch):
        if self._mode == "train":
            self._engine._state, metrics = self._engine._step(
                self._engine.state, batch)
            return metrics["loss"]
        return self._engine.evaluate([batch])["loss"]

    def state_dict(self):
        return dict(self._engine.state["params"])

    @property
    def engine(self):
        return self._engine


def to_static(model: Layer, data_loader=None, loss=None, optimizer=None,
              strategy=None, mesh=None) -> DistModel:
    """Reference: paddle.distributed.to_static — dygraph model + loader +
    loss + optimizer → distributed static model."""
    engine = Engine(model, loss=loss, optimizer=optimizer,
                    strategy=strategy, mesh=mesh)
    return DistModel(engine)
