"""paddle_tpu.fft — importable module form of the fft namespace.

Reference: python/paddle/fft.py.  Implementations live on ``ops.fft``
(jnp.fft plus the hermitian nd variants); this module hoists them so both
``paddle_tpu.fft.rfft`` and ``import paddle_tpu.fft`` work.
"""

from __future__ import annotations

from .ops import fft as _ns

_EXPORTED = [n for n in dir(_ns) if not n.startswith("_")]
for _n in _EXPORTED:
    globals()[_n] = getattr(_ns, _n)
del _n

__all__ = sorted(_EXPORTED)
