"""``paddle.vision`` parity: transforms, model zoo, ops, datasets.

Reference: python/paddle/vision/ (transforms/, models/, datasets/)
— SURVEY §2.6. Dataset downloads are gated (zero-egress image): the dataset
classes accept pre-downloaded files and there is a RandomDataset for tests.
"""

from . import transforms  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import datasets  # noqa: F401
from .models import *  # noqa: F401,F403 — the zoo's __all__ IS the
#                        paddle.vision re-export surface (one list to keep)
