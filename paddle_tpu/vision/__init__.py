"""``paddle.vision`` parity: transforms, model zoo (ResNet/LeNet), datasets.

Reference: python/paddle/vision/ (transforms/, models/resnet.py, datasets/)
— SURVEY §2.6. Dataset downloads are gated (zero-egress image): the dataset
classes accept pre-downloaded files and there is a RandomDataset for tests.
"""

from . import transforms  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import datasets  # noqa: F401
from .models import (LeNet, ResNet, resnet18, resnet34, resnet50,  # noqa: F401
                     VGG, vgg11, vgg13, vgg16, vgg19, AlexNet, alexnet,
                     SqueezeNet, squeezenet1_0, squeezenet1_1,
                     MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2,
                     DenseNet, densenet121)
