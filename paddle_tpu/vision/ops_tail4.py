"""Round-4 vision.ops tail: batched_nms, generate_proposals (RPN),
read_file/decode_jpeg.

Reference: python/paddle/vision/ops.py (SURVEY §2.6 vision row).
Tests: tests/test_vision_tail4.py.
"""

from __future__ import annotations

import io

import numpy as np
import jax
import jax.numpy as jnp

from .ops import box_iou, nms

__all__ = ["batched_nms", "generate_proposals", "read_file", "decode_jpeg"]


def batched_nms(boxes, scores, category_idxs, iou_threshold=0.3,
                top_k=None):
    """Reference: paddle.vision.ops.batched_nms — per-category NMS in one
    pass via the coordinate-offset trick: boxes of different categories
    are translated to disjoint regions so they can never suppress each
    other."""
    b = jnp.asarray(boxes)
    cat = jnp.asarray(category_idxs)
    span = jnp.max(b) - jnp.min(b) + 1.0
    shifted = b + (cat.astype(b.dtype) * span)[:, None]
    return nms(shifted, iou_threshold, scores=jnp.asarray(scores),
               top_k=top_k)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True):
    """Reference: paddle.vision.ops.generate_proposals — RPN head:
    decode anchor deltas, clip to image, drop tiny boxes, keep
    pre_nms_top_n by score, NMS, keep post_nms_top_n.

    Shapes: scores (N, A, H, W), bbox_deltas (N, 4*A, H, W),
    anchors/variances (H, W, A, 4).  Static-shape formulation: the NMS
    stage uses the padded fixed-top_k path (invalid slots get score 0 and
    are dropped at the end on host).
    """
    scores = jnp.asarray(scores)
    deltas = jnp.asarray(bbox_deltas)
    anchors = jnp.asarray(anchors).reshape(-1, 4)
    variances = jnp.asarray(variances).reshape(-1, 4)
    N, A = scores.shape[0], scores.shape[1]
    offset = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        sc = scores[n].transpose(1, 2, 0).reshape(-1)          # (HWA,)
        dl = deltas[n].reshape(A, 4, *deltas.shape[2:]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)              # (HWA, 4)
        # decode (the reference box_coder decode_center_size with variances)
        aw = anchors[:, 2] - anchors[:, 0] + offset
        ah = anchors[:, 3] - anchors[:, 1] + offset
        acx = anchors[:, 0] + 0.5 * aw
        acy = anchors[:, 1] + 0.5 * ah
        cx = variances[:, 0] * dl[:, 0] * aw + acx
        cy = variances[:, 1] * dl[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(variances[:, 2] * dl[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(variances[:, 3] * dl[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                           cx + 0.5 * w - offset, cy + 0.5 * h - offset],
                          axis=1)
        H, W = float(img_size[n][0]), float(img_size[n][1])
        boxes = jnp.clip(boxes, jnp.asarray([0.0, 0.0, 0.0, 0.0]),
                         jnp.asarray([W - offset, H - offset, W - offset,
                                      H - offset]))
        # drop boxes below min_size
        bw = boxes[:, 2] - boxes[:, 0] + offset
        bh = boxes[:, 3] - boxes[:, 1] + offset
        valid = (bw >= min_size) & (bh >= min_size)
        sc = jnp.where(valid, sc, -jnp.inf)
        k1 = min(int(pre_nms_top_n), sc.shape[0])
        top_sc, top_idx = jax.lax.top_k(sc, k1)
        top_boxes = boxes[top_idx]
        keep = nms(top_boxes, nms_thresh, scores=top_sc,
                   top_k=min(int(post_nms_top_n), k1))
        keep_np = np.asarray(keep)
        keep_np = keep_np[keep_np >= 0]
        rois = np.asarray(top_boxes)[keep_np]
        probs = np.asarray(top_sc)[keep_np]
        fin = np.isfinite(probs)
        all_rois.append(rois[fin])
        all_probs.append(probs[fin])
        nums.append(int(fin.sum()))
    rois = jnp.asarray(np.concatenate(all_rois, axis=0)) if all_rois else \
        jnp.zeros((0, 4))
    probs = jnp.asarray(np.concatenate(all_probs, axis=0))
    if return_rois_num:
        return rois, probs, jnp.asarray(np.asarray(nums, np.int32))
    return rois, probs


def read_file(path, name=None):
    """Reference: paddle.vision.ops.read_file — raw bytes as a uint8
    tensor (host IO, dataloader domain)."""
    with open(path, "rb") as f:
        data = f.read()
    return jnp.asarray(np.frombuffer(data, np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference: paddle.vision.ops.decode_jpeg — JPEG bytes → CHW uint8
    tensor.  Decoding runs on host (PIL); the reference's nvjpeg GPU path
    is IO-domain and stays off-chip here by design."""
    from PIL import Image
    buf = np.asarray(x).tobytes()
    img = Image.open(io.BytesIO(buf))
    if mode in ("gray", "grayscale", "L"):
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)
