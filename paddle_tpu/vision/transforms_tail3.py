"""Vision transforms tail (reference: python/paddle/vision/transforms/
{functional,transforms}.py members beyond the round-1 subset).

Host-side numpy on HWC uint8/float images, like the base module — these
run in DataLoader workers, not on the TPU.
"""

from __future__ import annotations

import math
import numbers

import numpy as np

from .transforms import CenterCrop, Normalize, ToTensor, _resize_np

__all__ = [
    "crop", "center_crop", "resize", "hflip", "vflip", "normalize", "pad",
    "rotate", "affine", "perspective", "erase", "adjust_brightness",
    "adjust_contrast", "adjust_saturation", "adjust_hue", "to_grayscale",
    "to_tensor",
    "RandomVerticalFlip", "Pad", "RandomRotation", "RandomResizedCrop",
    "ColorJitter", "Grayscale", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "RandomAffine",
    "RandomPerspective", "RandomErasing",
]


# ---------------------------------------------------------------------------
# functional
# ---------------------------------------------------------------------------

def _as_float(img):
    return img.astype(np.float32), img.dtype


def _restore(out, dtype):
    if dtype == np.uint8:
        return np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out.astype(dtype)


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(img, size)


def hflip(img):
    return img[:, ::-1].copy()


def vflip(img):
    return img[::-1].copy()


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, numbers.Number):
        pl = pr = pt_ = pb = int(padding)
    elif len(padding) == 2:
        pl, pt_ = int(padding[0]), int(padding[1])
        pr, pb = pl, pt_
    else:
        pl, pt_, pr, pb = (int(p) for p in padding)
    spec = [(pt_, pb), (pl, pr)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, spec, constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, spec, mode=mode)


def _warp_np(img, matrix, fill=0.0):
    """Inverse-warp with bilinear sampling: ``matrix`` (3x3) maps OUTPUT
    pixel coords (x, y, 1) to INPUT coords."""
    imgf, dtype = _as_float(img)
    if imgf.ndim == 2:
        imgf = imgf[:, :, None]
        squeeze = True
    else:
        squeeze = False
    h, w = imgf.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w]
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1).reshape(-1, 3).astype(
        np.float64)
    src = coords @ np.asarray(matrix, np.float64).T
    sx = src[:, 0] / src[:, 2]
    sy = src[:, 1] / src[:, 2]
    x0 = np.floor(sx).astype(int)
    y0 = np.floor(sy).astype(int)
    fx = (sx - x0)[:, None]
    fy = (sy - y0)[:, None]

    def sample(yy, xx):
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        out = np.full((yy.size, imgf.shape[2]), float(fill), np.float32)
        out[valid] = imgf[yy[valid], xx[valid]]
        return out

    out = (sample(y0, x0) * (1 - fy) * (1 - fx)
           + sample(y0, x0 + 1) * (1 - fy) * fx
           + sample(y0 + 1, x0) * fy * (1 - fx)
           + sample(y0 + 1, x0 + 1) * fy * fx)
    out = out.reshape(h, w, imgf.shape[2])
    if squeeze:
        out = out[:, :, 0]
    return _restore(out, dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) T(translate); invert for warp
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    fwd = np.array([[a * scale, b * scale,
                     cx + tx - (a * scale * cx + b * scale * cy)],
                    [c * scale, d * scale,
                     cy + ty - (c * scale * cx + d * scale * cy)],
                    [0, 0, 1]], np.float64)
    return np.linalg.inv(fwd)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    if isinstance(shear, numbers.Number):
        shear = (float(shear), 0.0)
    h, w = img.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    return _warp_np(img, _affine_matrix(angle, translate, scale, shear,
                                        center), fill)


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    if expand:
        h, w = img.shape[:2]
        rot = math.radians(angle)
        nw = int(round(abs(w * math.cos(rot)) + abs(h * math.sin(rot))))
        nh = int(round(abs(w * math.sin(rot)) + abs(h * math.cos(rot))))
        canvas_spec = ((nh - h + 1) // 2, (nw - w + 1) // 2)
        padded = np.pad(img, [(canvas_spec[0], nh - h - canvas_spec[0]),
                              (canvas_spec[1], nw - w - canvas_spec[1])]
                        + [(0, 0)] * (img.ndim - 2),
                        constant_values=fill)
        return rotate(padded, angle, interpolation, False, None, fill)
    return affine(img, angle=angle, fill=fill, center=center)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """Warp so that ``startpoints`` (in the input) land on ``endpoints``."""
    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bvec += [ex, ey]
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(bvec, np.float64))
    fwd = np.append(coeffs, 1.0).reshape(3, 3)
    return _warp_np(img, np.linalg.inv(fwd), fill)


def erase(img, i, j, h, w, v, inplace=False):
    out = img if inplace else img.copy()
    out[i:i + h, j:j + w] = v
    return out


def adjust_brightness(img, brightness_factor):
    imgf, dtype = _as_float(img)
    return _restore(imgf * brightness_factor, dtype)


def adjust_contrast(img, contrast_factor):
    imgf, dtype = _as_float(img)
    mean = to_grayscale(imgf).mean()
    return _restore((imgf - mean) * contrast_factor + mean, dtype)


def adjust_saturation(img, saturation_factor):
    imgf, dtype = _as_float(img)
    gray = to_grayscale(imgf, num_output_channels=img.shape[-1])
    return _restore(imgf * saturation_factor
                    + gray * (1 - saturation_factor), dtype)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5]: shift the HSV hue channel."""
    imgf, dtype = _as_float(img)
    scale = 255.0 if dtype == np.uint8 else 1.0
    x = imgf / scale
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = x.max(-1)
    minc = x.min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(delta == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    return _restore(np.stack([r2, g2, b2], axis=-1) * scale, dtype)


def to_grayscale(img, num_output_channels=1):
    imgf, dtype = _as_float(img)
    if imgf.ndim == 2:
        gray = imgf
    else:
        gray = (0.299 * imgf[..., 0] + 0.587 * imgf[..., 1]
                + 0.114 * imgf[..., 2])
    if num_output_channels == 1:
        out = gray[..., None] if img.ndim == 3 else gray
    else:
        out = np.stack([gray] * num_output_channels, axis=-1)
    return _restore(out, dtype)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


# ---------------------------------------------------------------------------
# transform classes
# ---------------------------------------------------------------------------

class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class RandomRotation:
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = ((size, size) if isinstance(size, int)
                     else tuple(size))
        self.scale, self.ratio = scale, ratio

    def __call__(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = math.exp(np.random.uniform(math.log(self.ratio[0]),
                                            math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                return resize(crop(img, top, left, ch, cw), self.size)
        return resize(center_crop(img, min(h, w)), self.size)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_brightness(img,
                                 np.random.uniform(max(0, 1 - self.value),
                                                   1 + self.value))


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img,
                               np.random.uniform(max(0, 1 - self.value),
                                                 1 + self.value))


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(img,
                                 np.random.uniform(max(0, 1 - self.value),
                                                   1 + self.value))


class HueTransform:
    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees, self.translate = degrees, translate
        self.scale, self.shear = scale, shear
        self.fill, self.center = fill, center

    def __call__(self, img):
        h, w = img.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = (np.random.uniform(*self.scale) if self.scale is not None
              else 1.0)
        sh = (0.0, 0.0)
        if self.shear is not None:
            shear = self.shear
            if isinstance(shear, numbers.Number):
                shear = (-shear, shear)
            sh = (np.random.uniform(shear[0], shear[1]), 0.0)
        return affine(img, angle, (tx, ty), sc, sh, fill=self.fill,
                      center=self.center)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0):
        self.prob, self.d = prob, distortion_scale
        self.fill = fill

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = img.shape[:2]
        dw = int(self.d * w / 2)
        dh = int(self.d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dw + 1), np.random.randint(0, dh + 1)),
               (w - 1 - np.random.randint(0, dw + 1),
                np.random.randint(0, dh + 1)),
               (w - 1 - np.random.randint(0, dw + 1),
                h - 1 - np.random.randint(0, dh + 1)),
               (np.random.randint(0, dw + 1),
                h - 1 - np.random.randint(0, dh + 1))]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing:
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(math.sqrt(target * ar)))
            ew = int(round(math.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
