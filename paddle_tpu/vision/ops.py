"""``paddle.vision.ops`` parity: detection primitives.

Reference: python/paddle/vision/ops.py (nms, roi_align, box coders;
backed by CUDA kernels in phi).

TPU redesign: everything is expressed as fixed-shape tensor math so it
jits — nms is the classic greedy suppression as a fori_loop over a
precomputed IoU matrix (no dynamic shapes: returns keep mask/indices
padded to ``top_k``); roi_align is gather-based bilinear sampling.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["box_iou", "nms", "roi_align",
           # round-3 tail (ops_tail3.py)
           "roi_pool", "psroi_pool", "deform_conv2d", "box_coder",
           "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
           "distribute_fpn_proposals",
           "RoIPool", "PSRoIPool", "RoIAlign", "DeformConv2D"]


def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] and [M,4] xyxy boxes → [N,M]."""
    a1, a2 = jnp.split(boxes1, 2, axis=-1)          # [N,2] mins / maxs
    b1, b2 = jnp.split(boxes2, 2, axis=-1)
    lt = jnp.maximum(a1[:, None], b1[None])          # [N,M,2]
    rb = jnp.minimum(a2[:, None], b2[None])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.prod(jnp.clip(a2 - a1, 0), axis=-1)
    area_b = jnp.prod(jnp.clip(b2 - b1, 0), axis=-1)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-9)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        top_k: Optional[int] = None):
    """Greedy non-maximum suppression (reference: paddle.vision.ops.nms).

    Returns indices of kept boxes in descending score order. Without
    ``top_k`` the result is a concrete (host) int array; with ``top_k``
    the shape is static [top_k] padded with -1, usable under jit.
    """
    n = boxes.shape[0]
    scores = jnp.arange(n, 0, -1, dtype=jnp.float32) if scores is None \
        else jnp.asarray(scores)
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = box_iou(sorted_boxes, sorted_boxes)

    def body(i, keep):
        # drop i if it overlaps any earlier KEPT box beyond the threshold
        overlap = (iou[i] > iou_threshold) & keep & \
            (jnp.arange(n) < i)
        return keep.at[i].set(~overlap.any())

    keep = jax.lax.fori_loop(1, n, body, jnp.ones((n,), bool))
    if top_k is None:
        idx = jnp.nonzero(keep)[0]          # host-concrete path
        return order[idx]
    ranked = jnp.where(keep, jnp.arange(n), n)
    sel = jnp.sort(ranked)[:top_k]
    return jnp.where(sel < n, order[jnp.clip(sel, 0, n - 1)], -1)


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (reference: paddle.vision.ops.roi_align).

    x: [N,C,H,W]; boxes: [K,4] xyxy in input coords; ``boxes_num``: [N]
    rois per image (defaults: all rois on image 0). → [K,C,oh,ow].
    """
    oh, ow = ((output_size, output_size)
              if isinstance(output_size, int) else tuple(output_size))
    n, c, h, w = x.shape
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(n), boxes_num,
                               total_repeat_length=k)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:  # legacy: clamp to min size 1
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    if sampling_ratio > 0:
        sr_cap = int(sampling_ratio)
        sr_y = jnp.full((k,), float(sr_cap), jnp.float32)
        sr_x = sr_y
    else:
        # adaptive (reference/torchvision): ceil(roi extent / output bins)
        # samples per bin, per roi. Shapes must stay static on TPU, so the
        # grid is sr_cap wide with per-roi validity masks; rois larger than
        # sr_cap× the output grid sample sr_cap points per bin (documented
        # deviation). With concrete boxes (eager path) the cap is tightened
        # to what the batch actually needs, so small rois don't pay for the
        # full masked grid.
        sr_cap = 8
        if not isinstance(rh, jax.core.Tracer):
            import math
            need = max(
                [1.0] + [math.ceil(float(e) / n) for e, n in
                         [(float(jnp.max(rh)), oh), (float(jnp.max(rw)), ow)]])
            sr_cap = max(1, min(sr_cap, int(need)))
        sr_y = jnp.clip(jnp.ceil(rh / oh), 1.0, float(sr_cap))
        sr_x = jnp.clip(jnp.ceil(rw / ow), 1.0, float(sr_cap))

    # sample grid: up to sr_cap×sr_cap points per output bin, masked to the
    # per-roi (sr_y, sr_x) counts and averaged
    def bin_coords(start, extent, nbins, sr_vec):
        # [K, nbins, sr_cap]: start + (bin + (s+0.5)/sr_roi) * extent/nbins
        s = jnp.arange(sr_cap)
        b = jnp.arange(nbins)
        pos = (start[:, None, None]
               + (b[None, :, None] + (s[None, None, :] + 0.5)
                  / sr_vec[:, None, None])
               * (extent / nbins)[:, None, None])
        valid = s[None, None, :] < sr_vec[:, None, None]
        return pos, valid

    ys, yv = bin_coords(y1, rh, oh, sr_y)           # [K, oh, sr_cap]
    xs, xv = bin_coords(x1, rw, ow, sr_x)           # [K, ow, sr_cap]

    def bilinear(img, yy, xx):
        """img: [C,H,W]; yy/xx: [P] → [P,C]"""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = (yy - y0)[:, None]
        wx = (xx - x0)[:, None]

        def at(yi, xi):
            inside = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
            v = img[:, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
                    jnp.clip(xi, 0, w - 1).astype(jnp.int32)]  # [C,P]
            return jnp.where(inside[None], v, 0.0).T             # [P,C]

        return (at(y0, x0) * (1 - wy) * (1 - wx)
                + at(y0, x0 + 1) * (1 - wy) * wx
                + at(y0 + 1, x0) * wy * (1 - wx)
                + at(y0 + 1, x0 + 1) * wy * wx)

    def roi_pool(i):
        img = x[batch_idx[i]]
        ys_r = ys[i]                                 # [oh, sr_cap]
        xs_r = xs[i]                                 # [ow, sr_cap]
        yy = jnp.tile(ys_r[:, None, :, None], (1, ow, 1, sr_cap)).reshape(-1)
        xx = jnp.tile(xs_r[None, :, None, :], (oh, 1, sr_cap, 1)).reshape(-1)
        vv = (jnp.tile(yv[i][:, None, :, None], (1, ow, 1, sr_cap))
              & jnp.tile(xv[i][None, :, None, :], (oh, 1, sr_cap, 1))
              ).reshape(-1)
        vals = bilinear(img, yy, xx)                 # [oh*ow*cap*cap, C]
        vals = jnp.where(vv[:, None], vals, 0.0)
        vals = (vals.reshape(oh, ow, sr_cap * sr_cap, c).sum(axis=2)
                / (sr_y[i] * sr_x[i]))
        return jnp.moveaxis(vals, -1, 0)             # [C, oh, ow]

    return jax.vmap(roi_pool)(jnp.arange(k))


# round-3 tail (roi/psroi pooling, deformable conv, SSD/YOLO box ops,
# matrix NMS, FPN routing) — see ops_tail3.py
from .ops_tail3 import *  # noqa: E402,F401,F403
from .ops_tail4 import *  # noqa: E402,F401,F403
from .ops_tail4 import __all__ as _t4_all  # noqa: E402
__all__ += _t4_all
