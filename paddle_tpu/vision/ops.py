"""``paddle.vision.ops`` parity: detection primitives.

Reference: python/paddle/vision/ops.py (nms, roi_align, box coders;
backed by CUDA kernels in phi).

TPU redesign: everything is expressed as fixed-shape tensor math so it
jits — nms is the classic greedy suppression as a fori_loop over a
precomputed IoU matrix (no dynamic shapes: returns keep mask/indices
padded to ``top_k``); roi_align is gather-based bilinear sampling.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["box_iou", "nms", "roi_align"]


def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] and [M,4] xyxy boxes → [N,M]."""
    a1, a2 = jnp.split(boxes1, 2, axis=-1)          # [N,2] mins / maxs
    b1, b2 = jnp.split(boxes2, 2, axis=-1)
    lt = jnp.maximum(a1[:, None], b1[None])          # [N,M,2]
    rb = jnp.minimum(a2[:, None], b2[None])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.prod(jnp.clip(a2 - a1, 0), axis=-1)
    area_b = jnp.prod(jnp.clip(b2 - b1, 0), axis=-1)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-9)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        top_k: Optional[int] = None):
    """Greedy non-maximum suppression (reference: paddle.vision.ops.nms).

    Returns indices of kept boxes in descending score order. Without
    ``top_k`` the result is a concrete (host) int array; with ``top_k``
    the shape is static [top_k] padded with -1, usable under jit.
    """
    n = boxes.shape[0]
    scores = jnp.arange(n, 0, -1, dtype=jnp.float32) if scores is None \
        else jnp.asarray(scores)
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = box_iou(sorted_boxes, sorted_boxes)

    def body(i, keep):
        # drop i if it overlaps any earlier KEPT box beyond the threshold
        overlap = (iou[i] > iou_threshold) & keep & \
            (jnp.arange(n) < i)
        return keep.at[i].set(~overlap.any())

    keep = jax.lax.fori_loop(1, n, body, jnp.ones((n,), bool))
    if top_k is None:
        idx = jnp.nonzero(keep)[0]          # host-concrete path
        return order[idx]
    ranked = jnp.where(keep, jnp.arange(n), n)
    sel = jnp.sort(ranked)[:top_k]
    return jnp.where(sel < n, order[jnp.clip(sel, 0, n - 1)], -1)


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (reference: paddle.vision.ops.roi_align).

    x: [N,C,H,W]; boxes: [K,4] xyxy in input coords; ``boxes_num``: [N]
    rois per image (defaults: all rois on image 0). → [K,C,oh,ow].
    """
    oh, ow = ((output_size, output_size)
              if isinstance(output_size, int) else tuple(output_size))
    n, c, h, w = x.shape
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(n), boxes_num,
                               total_repeat_length=k)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:  # legacy: clamp to min size 1
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    sr = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: sr×sr points per output bin, averaged
    def bin_coords(start, extent, nbins):
        # [K, nbins, sr]: start + (bin + (s+0.5)/sr) * extent/nbins
        s = (jnp.arange(sr) + 0.5) / sr
        b = jnp.arange(nbins)
        return (start[:, None, None]
                + (b[None, :, None] + s[None, None, :])
                * (extent / nbins)[:, None, None])

    ys = bin_coords(y1, rh, oh)                     # [K, oh, sr]
    xs = bin_coords(x1, rw, ow)                     # [K, ow, sr]

    def bilinear(img, yy, xx):
        """img: [C,H,W]; yy/xx: [P] → [P,C]"""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = (yy - y0)[:, None]
        wx = (xx - x0)[:, None]

        def at(yi, xi):
            inside = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
            v = img[:, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
                    jnp.clip(xi, 0, w - 1).astype(jnp.int32)]  # [C,P]
            return jnp.where(inside[None], v, 0.0).T             # [P,C]

        return (at(y0, x0) * (1 - wy) * (1 - wx)
                + at(y0, x0 + 1) * (1 - wy) * wx
                + at(y0 + 1, x0) * wy * (1 - wx)
                + at(y0 + 1, x0 + 1) * wy * wx)

    def roi_pool(i):
        img = x[batch_idx[i]]
        ys_r = ys[i]                                 # [oh, sr]
        xs_r = xs[i]                                 # [ow, sr]
        yy = jnp.tile(ys_r[:, None, :, None], (1, ow, 1, sr)).reshape(-1)
        xx = jnp.tile(xs_r[None, :, None, :], (oh, 1, sr, 1)).reshape(-1)
        vals = bilinear(img, yy, xx)                 # [oh*ow*sr*sr, C]
        vals = vals.reshape(oh, ow, sr * sr, c).mean(axis=2)
        return jnp.moveaxis(vals, -1, 0)             # [C, oh, ow]

    return jax.vmap(roi_pool)(jnp.arange(k))
