"""Round-3 vision ops tail (reference: python/paddle/vision/ops.py).

Static-shape XLA formulations; oracle tests in
tests/test_vision_tail3.py.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..nn.layer import Layer

__all__ = ["roi_pool", "psroi_pool", "deform_conv2d", "box_coder",
           "prior_box", "yolo_box", "matrix_nms",
           "distribute_fpn_proposals", "yolo_loss",
           "RoIPool", "PSRoIPool", "RoIAlign", "DeformConv2D"]


def _batch_index(boxes_num, n, k):
    if boxes_num is None:
        return jnp.zeros((k,), jnp.int32)
    return jnp.repeat(jnp.arange(n), boxes_num, total_repeat_length=k)


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """Reference: paddle.vision.ops.roi_pool — max-pool each RoI into a
    fixed [oh, ow] grid (quantized bin edges, Fast R-CNN semantics)."""
    oh, ow = ((output_size, output_size)
              if isinstance(output_size, int) else tuple(output_size))
    n, c, h, w = x.shape
    k = boxes.shape[0]
    bidx = _batch_index(boxes_num, n, k)
    b = jnp.round(boxes * spatial_scale).astype(jnp.int32)
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    rw = jnp.maximum(x2 - x1 + 1, 1)
    rh = jnp.maximum(y2 - y1 + 1, 1)

    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def one_bin(i, j):
        # bin [i, j] covers rows floor(i*rh/oh) .. ceil((i+1)*rh/oh)
        y_lo = y1 + (i * rh) // oh
        y_hi = y1 + -((-(i + 1) * rh) // oh)   # ceil div
        x_lo = x1 + (j * rw) // ow
        x_hi = x1 + -((-(j + 1) * rw) // ow)
        ymask = (ys[None, :] >= y_lo[:, None]) & (ys[None, :] < jnp.maximum(y_hi, y_lo + 1)[:, None]) & \
                (ys[None, :] >= 0) & (ys[None, :] < h)
        xmask = (xs[None, :] >= x_lo[:, None]) & (xs[None, :] < jnp.maximum(x_hi, x_lo + 1)[:, None]) & \
                (xs[None, :] >= 0) & (xs[None, :] < w)
        m = ymask[:, None, :, None] & xmask[:, None, None, :]   # (k,1,h,w)
        feats = x[bidx]                                          # (k,c,h,w)
        neg = jnp.finfo(x.dtype).min
        return jnp.max(jnp.where(m, feats, neg), axis=(2, 3))

    out = jnp.stack([jnp.stack([one_bin(i, j) for j in range(ow)], axis=-1)
                     for i in range(oh)], axis=-2)
    return out  # (k, c, oh, ow)


def psroi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """Reference: paddle.vision.ops.psroi_pool — position-sensitive RoI
    average pool: input channels C = out_c * oh * ow; bin (i, j) reads its
    own channel group (R-FCN)."""
    oh, ow = ((output_size, output_size)
              if isinstance(output_size, int) else tuple(output_size))
    n, c, h, w = x.shape
    out_c = c // (oh * ow)
    k = boxes.shape[0]
    bidx = _batch_index(boxes_num, n, k)
    bx = boxes * spatial_scale
    x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    ys = jnp.arange(h)
    xs = jnp.arange(w)
    feats = x[bidx].reshape(k, oh, ow, out_c, h, w)

    def one_bin(i, j):
        y_lo = jnp.floor(y1 + i * rh / oh).astype(jnp.int32)
        y_hi = jnp.ceil(y1 + (i + 1) * rh / oh).astype(jnp.int32)
        x_lo = jnp.floor(x1 + j * rw / ow).astype(jnp.int32)
        x_hi = jnp.ceil(x1 + (j + 1) * rw / ow).astype(jnp.int32)
        ymask = (ys[None, :] >= jnp.clip(y_lo, 0, h)[:, None]) & \
                (ys[None, :] < jnp.clip(y_hi, 0, h)[:, None])
        xmask = (xs[None, :] >= jnp.clip(x_lo, 0, w)[:, None]) & \
                (xs[None, :] < jnp.clip(x_hi, 0, w)[:, None])
        m = (ymask[:, None, :, None] & xmask[:, None, None, :])
        cnt = jnp.maximum(m.sum(axis=(2, 3)), 1)
        grp = feats[:, i, j]                         # (k, out_c, h, w)
        return jnp.where(m, grp, 0.0).sum(axis=(2, 3)) / cnt

    out = jnp.stack([jnp.stack([one_bin(i, j) for j in range(ow)], axis=-1)
                     for i in range(oh)], axis=-2)
    return out  # (k, out_c, oh, ow)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Reference: paddle.vision.ops.deform_conv2d (DCNv1/v2).

    x: [N,Cin,H,W]; offset: [N, 2*dg*kh*kw, Ho, Wo] (y then x per tap,
    reference layout); mask: [N, dg*kh*kw, Ho, Wo] (v2 modulation).
    Gather-based bilinear sampling + one matmul — the XLA-native layout
    of the CUDA im2col kernel."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    ho = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    wo = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    dg = deformable_groups

    base_y = (jnp.arange(ho) * s[0] - p[0])[:, None, None]      # (ho,1,1)
    base_x = (jnp.arange(wo) * s[1] - p[1])[None, :, None]      # (1,wo,1)
    tap_y = (jnp.arange(kh) * d[0])[None, None, :, None]        # ky
    tap_x = (jnp.arange(kw) * d[1])[None, None, None, :]        # kx
    # offsets: [N, dg, kh, kw, 2, Ho, Wo] (y, x)
    off = offset.reshape(n, dg, kh, kw, 2, ho, wo)
    oy = off[:, :, :, :, 0].transpose(0, 1, 4, 5, 2, 3)  # (n,dg,ho,wo,kh,kw)
    ox = off[:, :, :, :, 1].transpose(0, 1, 4, 5, 2, 3)
    py = (base_y[None, None, :, :, :, None] + tap_y[None, None] + oy)
    px = (base_x[None, None, :, :, None, :] + tap_x[None, None] + ox)
    # bilinear sample each (n, dg, ho, wo, kh, kw) position per channel
    cg = cin // dg
    xg = x.reshape(n, dg, cg, h, w)

    y0 = jnp.floor(py).astype(jnp.int32)
    x0 = jnp.floor(px).astype(jnp.int32)
    fy = py - y0
    fx = px - x0
    samples = 0.0
    for dy, wy in ((0, 1 - fy), (1, fy)):
        for dx, wx in ((0, 1 - fx), (1, fx)):
            yy = y0 + dy
            xx = x0 + dx
            valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
            yi = jnp.clip(yy, 0, h - 1)
            xi = jnp.clip(xx, 0, w - 1)
            # vmap the gather over batch and deformable group
            def take(xg_bd, yi_bd, xi_bd):
                return xg_bd[:, yi_bd, xi_bd]       # (cg, ho,wo,kh,kw)
            g = jax.vmap(jax.vmap(take))(xg, yi, xi)
            samples = samples + g * (wy * wx * valid)[:, :, None]
    # samples: (n, dg, cg, ho, wo, kh, kw)
    if mask is not None:
        m = mask.reshape(n, dg, kh, kw, ho, wo).transpose(0, 1, 4, 5, 2, 3)
        samples = samples * m[:, :, None]
    cols = samples.reshape(n, cin, ho, wo, kh * kw)
    wg = weight.reshape(groups, cout // groups, cin_g, kh * kw)
    xcols = cols.reshape(n, groups, cin // groups, ho, wo, kh * kw)
    out = jnp.einsum("ngchwk,gock->ngohw", xcols, wg,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, cout, ho, wo).astype(x.dtype)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Reference: paddle.vision.ops.box_coder (SSD box transforms)."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        if var.ndim == 1:
            vx, vy, vw, vh = var[0], var[1], var[2], var[3]
        else:
            vx, vy, vw, vh = var[:, 0], var[:, 1], var[:, 2], var[:, 3]
        out = jnp.stack([(tcx[None] - pcx[:, None]) / pw[:, None],
                         (tcy[None] - pcy[:, None]) / ph[:, None],
                         jnp.log(tw[None] / pw[:, None]),
                         jnp.log(th[None] / ph[:, None])], axis=-1)
        return out / jnp.reshape(jnp.stack([vx, vy, vw, vh], -1),
                                 (-1, 1, 4) if var.ndim > 1 else (1, 1, 4))
    # decode_center_size: target [N(priors), M, 4] deltas against priors
    if tb.ndim == 2:
        tb = tb[:, None]
    if var.ndim == 1:
        tb = tb * var                       # (4,) broadcasts over all dims
    else:
        # per-prior variance: broadcast along the prior axis
        tb = tb * var[:, None, :]
    dx, dy, dw, dh = tb[..., 0], tb[..., 1], tb[..., 2], tb[..., 3]
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    bw = jnp.exp(dw) * pw[:, None]
    bh = jnp.exp(dh) * ph[:, None]
    return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                      cx + bw * 0.5 - norm, cy + bh * 0.5 - norm], axis=-1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """Reference: paddle.vision.ops.prior_box (SSD anchors)."""
    _, _, fh, fw = input.shape
    _, _, ih, iw = image.shape
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        boxes.append((ms, ms))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            boxes.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            boxes.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
    num = len(boxes)
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                     # (fh, fw)
    bw = jnp.asarray([b[0] for b in boxes], jnp.float32) / 2
    bh = jnp.asarray([b[1] for b in boxes], jnp.float32) / 2
    out = jnp.stack([
        (cxg[..., None] - bw) / iw, (cyg[..., None] - bh) / ih,
        (cxg[..., None] + bw) / iw, (cyg[..., None] + bh) / ih], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           (fh, fw, num, 4))
    return out, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Reference: paddle.vision.ops.yolo_box (YOLOv3 head decode)."""
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jnp.arange(w))[None, None, None, :]
    gy = (jnp.arange(h))[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (gx + sig(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2) / w
    by = (gy + sig(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (
        downsample_ratio * w)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (
        downsample_ratio * h)
    conf = sig(x[:, :, 4])
    probs = sig(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(jnp.float32)[:, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None]
    flat = lambda a: a.reshape(n, -1)
    x1 = flat(bx - bw / 2) * imw
    y1 = flat(by - bh / 2) * imh
    x2 = flat(bx + bw / 2) * imw
    y2 = flat(by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    keep = flat(conf) > conf_thresh
    scores = jnp.where(keep[..., None], scores, 0.0)
    # reference kernel emits all-zero rows for suppressed anchors (ported
    # consumers filter on boxes.sum(-1) != 0)
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    return boxes, scores


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=100, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=-1, normalized=True):
    """Reference: paddle.vision.ops.matrix_nms (SOLOv2) — soft decay of
    each box's score by its IoU with higher-scored same-class boxes.
    Single-image [M,4] boxes / [C,M] scores; returns (out [K,6], index)."""
    from .ops import box_iou
    c, m = scores.shape
    top = min(nms_top_k, m)
    out_rows = []
    idx_rows = []
    for cls in range(c):
        if cls == background_label:
            continue
        # reference filters score_threshold BEFORE NMS: below-threshold
        # boxes must not enter the top_k set nor influence decay — push
        # them to the sort tail, where they can never be "higher-scored"
        s = jnp.where(scores[cls] > score_threshold, scores[cls], -jnp.inf)
        order = jnp.argsort(-s)[:top]
        sc = s[order]
        bx = bboxes[order]
        iou = box_iou(bx, bx)
        tri = jnp.tril(iou, k=-1)       # tri[j, i] = iou with higher-scored i
        # compensate term: each HIGHER box i's own max IoU with boxes above
        # it (SOLOv2 eq. 4) — a row max, indexed by i in the decay
        comp = tri.max(axis=1)
        if use_gaussian:
            decay = jnp.exp(-(tri ** 2 - comp[None, :] ** 2)
                            / gaussian_sigma).min(axis=1)
        else:
            decay = ((1 - tri) / (1 - comp[None, :] + 1e-12)).min(axis=1)
        dec = jnp.where(jnp.arange(top) == 0, 1.0, decay)
        new_s = sc * dec
        # post_threshold applies to DECAYED scores (pre-filter already
        # removed sub-score_threshold candidates above)
        valid = jnp.isfinite(new_s) & (new_s > post_threshold)
        out_rows.append(jnp.concatenate(
            [jnp.full((top, 1), cls, jnp.float32),
             jnp.where(valid, new_s, 0.0)[:, None], bx], axis=1))
        idx_rows.append(order)
    out = jnp.concatenate(out_rows, axis=0)
    idx = jnp.concatenate(idx_rows, axis=0)
    order = jnp.argsort(-out[:, 1])[:keep_top_k]
    return out[order], idx[order]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None):
    """Reference: paddle.vision.ops.distribute_fpn_proposals — route each
    RoI to an FPN level by its scale.  Static-shape variant: returns one
    [K,4] tensor per level with non-member rows zeroed + a mask list +
    the restore index."""
    off = 1.0 if pixel_offset else 0.0
    w = fpn_rois[:, 2] - fpn_rois[:, 0] + off
    h = fpn_rois[:, 3] - fpn_rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs, masks = [], []
    for level in range(min_level, max_level + 1):
        m = lvl == level
        outs.append(jnp.where(m[:, None], fpn_rois, 0.0))
        masks.append(m)
    restore = jnp.argsort(jnp.argsort(lvl, stable=True), stable=True)
    return outs, masks, restore


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num=None):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num=None):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0, sampling_ratio=-1,
                 aligned=True):
        super().__init__()
        self.args = (output_size, spatial_scale, sampling_ratio,
                     aligned)

    def forward(self, x, boxes, boxes_num=None):
        from .ops import roi_align
        return roi_align(x, boxes, boxes_num, *self.args)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        from ..nn import initializer as I
        fan_in = in_channels * k[0] * k[1]
        bound = 1 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]),
            attr=weight_attr, default_initializer=I.Uniform(-bound, bound))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """Reference: paddle.vision.ops.yolo_loss (YOLOv3 head loss).

    x: (N, C, H, W) raw head output, C = len(anchor_mask)*(5+class_num);
    gt_box: (N, B, 4) normalized center-format (cx, cy, w, h) in [0, 1];
    gt_label: (N, B) int class ids; rows with w*h == 0 are padding.

    Faithful to the YOLOv3 recipe the reference implements: BCE for
    x/y/objectness/class, squared error for w/h targets in log-anchor
    space, (2 - w*h) box-size weighting, responsible anchor chosen by
    wh-IoU over ALL anchors, negatives with best pred-IoU > ignore_thresh
    dropped from the objectness loss.  Returns (N,) per-image loss."""
    n, c, h, w = x.shape
    na = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = an_all[jnp.asarray(anchor_mask)]
    x = x.reshape(n, na, 5 + class_num, h, w)
    px, py = x[:, :, 0], x[:, :, 1]
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]

    gt_box = jnp.asarray(gt_box, jnp.float32)
    b = gt_box.shape[1]
    valid = (gt_box[:, :, 2] * gt_box[:, :, 3]) > 0           # (N, B)
    if gt_score is None:
        gt_score = jnp.ones((n, b), jnp.float32)

    # responsible anchor per gt: best wh-IoU over ALL anchors (then kept
    # only if it belongs to this head's anchor_mask)
    gw = gt_box[:, :, 2] * w * downsample_ratio               # pixels
    gh = gt_box[:, :, 3] * h * downsample_ratio
    inter = (jnp.minimum(gw[:, :, None], an_all[None, None, :, 0])
             * jnp.minimum(gh[:, :, None], an_all[None, None, :, 1]))
    union = (gw * gh)[:, :, None] + \
        (an_all[:, 0] * an_all[:, 1])[None, None] - inter
    best_anchor = jnp.argmax(inter / (union + 1e-9), axis=-1)  # (N, B)
    mask_arr = jnp.asarray(anchor_mask)
    local_a = jnp.argmax(best_anchor[:, :, None] == mask_arr[None, None],
                         axis=-1)                              # (N, B)
    owned = (best_anchor[:, :, None] == mask_arr[None, None]).any(-1)
    valid = valid & owned

    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
    tx = gt_box[:, :, 0] * w - gi                              # in (0,1)
    ty = gt_box[:, :, 1] * h - gj
    tw = jnp.log(jnp.maximum(gw, 1e-9)
                 / jnp.maximum(an[local_a][:, :, 0], 1e-9))
    th = jnp.log(jnp.maximum(gh, 1e-9)
                 / jnp.maximum(an[local_a][:, :, 1], 1e-9))
    box_w = 2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]            # size weight

    rows = jnp.arange(n)[:, None]

    def bce(logit, target):
        return jax.nn.softplus(logit) - logit * target

    p_at = lambda t: t[rows, local_a, gj, gi]                  # (N, B)
    vw = jnp.where(valid, gt_score * box_w, 0.0)
    loss_xy = vw * (bce(p_at(px), tx) + bce(p_at(py), ty))
    # w/h: L1 (the reference yolov3_loss op uses abs, not squared error)
    loss_wh = vw * (jnp.abs(p_at(pw) - tw) + jnp.abs(p_at(ph) - th))

    # class loss at the responsible cells; reference label smoothing:
    # positive target 1 - 1/C, negative target 1/C
    onehot = jax.nn.one_hot(jnp.asarray(gt_label, jnp.int32), class_num)
    if use_label_smooth and class_num > 1:
        delta = 1.0 / class_num
        onehot = onehot * (1.0 - delta) + (1 - onehot) * delta
    pc = pcls[rows, local_a, :, gj, gi]                        # (N, B, C)
    loss_cls = jnp.where(valid, gt_score, 0.0) * \
        (jax.nn.softplus(pc) - pc * onehot).sum(-1)

    # objectness: positives at responsible cells; negatives everywhere
    # else EXCEPT cells whose best-gt IoU exceeds ignore_thresh
    obj_t = jnp.zeros((n, na, h, w))
    obj_t = obj_t.at[rows, local_a, gj, gi].max(
        jnp.where(valid, gt_score, 0.0))
    pos = obj_t > 0
    # predicted boxes (decoded) vs gt IoU for the ignore mask
    cgx = (jnp.arange(w)[None, None, None, :]
           + jax.nn.sigmoid(px) * scale_x_y - (scale_x_y - 1) / 2) / w
    cgy = (jnp.arange(h)[None, None, :, None]
           + jax.nn.sigmoid(py) * scale_x_y - (scale_x_y - 1) / 2) / h
    bw_ = jnp.exp(pw) * an[None, :, 0, None, None] / (w * downsample_ratio)
    bh_ = jnp.exp(ph) * an[None, :, 1, None, None] / (h * downsample_ratio)

    def iou_with_gt(cx, cy, bw, bh):
        # (N, A, H, W) boxes vs (N, B) gts -> best IoU (N, A, H, W)
        px1, py1 = cx - bw / 2, cy - bh / 2
        px2, py2 = cx + bw / 2, cy + bh / 2
        gx1 = (gt_box[:, :, 0] - gt_box[:, :, 2] / 2)
        gy1 = (gt_box[:, :, 1] - gt_box[:, :, 3] / 2)
        gx2 = (gt_box[:, :, 0] + gt_box[:, :, 2] / 2)
        gy2 = (gt_box[:, :, 1] + gt_box[:, :, 3] / 2)
        sh4 = (n, 1, 1, 1, b)
        ix = jnp.maximum(
            0.0, jnp.minimum(px2[..., None], gx2.reshape(sh4))
            - jnp.maximum(px1[..., None], gx1.reshape(sh4)))
        iy = jnp.maximum(
            0.0, jnp.minimum(py2[..., None], gy2.reshape(sh4))
            - jnp.maximum(py1[..., None], gy1.reshape(sh4)))
        inter = ix * iy
        area_p = (bw * bh)[..., None]
        area_g = (gt_box[:, :, 2] * gt_box[:, :, 3]).reshape(sh4)
        iou = inter / (area_p + area_g - inter + 1e-9)
        return jnp.where(valid.reshape(sh4), iou, 0.0).max(-1)

    best_iou = iou_with_gt(cgx, cgy, bw_, bh_)
    neg_w = jnp.where(pos, 0.0,
                      jnp.where(best_iou > ignore_thresh, 0.0, 1.0))
    loss_obj = (jnp.where(pos, bce(pobj, obj_t), 0.0)
                + neg_w * bce(pobj, jnp.zeros_like(pobj)))
    return (loss_xy.sum(-1) + loss_wh.sum(-1) + loss_cls.sum(-1)
            + loss_obj.sum((1, 2, 3)))
