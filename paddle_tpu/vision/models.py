"""Vision model zoo: ResNet family + LeNet.

Reference: python/paddle/vision/models/resnet.py, lenet.py. BatchNorm+conv
blocks lower to XLA convs on the MXU; NCHW API kept for porting parity.
"""

from __future__ import annotations

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import (AvgPool2D, BatchNorm2D, Conv2D, Linear,
                                MaxPool2D, ReLU, Sequential)
from ..nn.layers_conv import AdaptiveAvgPool2D

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "LeNet"]


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1):
        super().__init__()
        self.conv1 = Conv2D(in_ch, ch, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(ch)
        self.conv2 = Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(ch)
        self.down = None
        if stride != 1 or in_ch != ch * self.expansion:
            self.down = Sequential(
                Conv2D(in_ch, ch * self.expansion, 1, stride=stride,
                       bias_attr=False),
                BatchNorm2D(ch * self.expansion))

    def forward(self, x):
        identity = x if self.down is None else self.down(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1):
        super().__init__()
        self.conv1 = Conv2D(in_ch, ch, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(ch)
        self.conv2 = Conv2D(ch, ch, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn2 = BatchNorm2D(ch)
        self.conv3 = Conv2D(ch, ch * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(ch * 4)
        self.down = None
        if stride != 1 or in_ch != ch * 4:
            self.down = Sequential(
                Conv2D(in_ch, ch * 4, 1, stride=stride, bias_attr=False),
                BatchNorm2D(ch * 4))

    def forward(self, x):
        identity = x if self.down is None else self.down(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


_CONFIGS = {
    18: (BasicBlock, (2, 2, 2, 2)),
    34: (BasicBlock, (3, 4, 6, 3)),
    50: (BottleneckBlock, (3, 4, 6, 3)),
    101: (BottleneckBlock, (3, 4, 23, 3)),
    152: (BottleneckBlock, (3, 8, 36, 3)),
}


class ResNet(Layer):
    def __init__(self, depth: int = 50, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        block, layers = _CONFIGS[depth]
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        ch = 64
        stages = []
        for i, (n, width) in enumerate(zip(layers, (64, 128, 256, 512))):
            blocks = []
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                blocks.append(block(ch, width, stride))
                ch = width * block.expansion
            stages.append(Sequential(*blocks))
        self.layer1, self.layer2, self.layer3, self.layer4 = stages
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def resnet18(num_classes=1000, **kw):
    return ResNet(18, num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(34, num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(50, num_classes=num_classes, **kw)


class LeNet(Layer):
    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, stride=2),
            Conv2D(6, 16, 5, stride=1), ReLU(),
            MaxPool2D(2, stride=2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.reshape(x.shape[0], -1))
