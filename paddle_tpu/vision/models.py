"""Vision model zoo: ResNet (+ResNeXt/WideResNet), LeNet, VGG, AlexNet,
SqueezeNet, MobileNetV1/V2, DenseNet, ShuffleNetV2, GoogLeNet.

Reference: python/paddle/vision/models/{resnet,lenet,vgg,alexnet,squeezenet,
mobilenetv1,mobilenetv2,densenet,shufflenetv2,googlenet}.py. BatchNorm+conv blocks lower to XLA
convs on the MXU; NCHW API kept for porting parity.
"""

from __future__ import annotations

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import (AvgPool2D, BatchNorm2D, Conv2D, Dropout,
                                Linear, MaxPool2D, ReLU, Sequential)
from ..nn.layers_conv import AdaptiveAvgPool2D

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "LeNet",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "AlexNet", "alexnet",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2",
    "DenseNet", "densenet121",
]


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1):
        super().__init__()
        self.conv1 = Conv2D(in_ch, ch, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(ch)
        self.conv2 = Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(ch)
        self.down = None
        if stride != 1 or in_ch != ch * self.expansion:
            self.down = Sequential(
                Conv2D(in_ch, ch * self.expansion, 1, stride=stride,
                       bias_attr=False),
                BatchNorm2D(ch * self.expansion))

    def forward(self, x):
        identity = x if self.down is None else self.down(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, groups=1, base_width=64):
        super().__init__()
        # ResNeXt/WideResNet parameterization (reference resnet.py):
        # the 3x3 runs at width = ch * base_width/64 with `groups` groups
        width = int(ch * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(in_ch, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, ch * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(ch * 4)
        self.down = None
        if stride != 1 or in_ch != ch * 4:
            self.down = Sequential(
                Conv2D(in_ch, ch * 4, 1, stride=stride, bias_attr=False),
                BatchNorm2D(ch * 4))

    def forward(self, x):
        identity = x if self.down is None else self.down(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


_CONFIGS = {
    18: (BasicBlock, (2, 2, 2, 2)),
    34: (BasicBlock, (3, 4, 6, 3)),
    50: (BottleneckBlock, (3, 4, 6, 3)),
    101: (BottleneckBlock, (3, 4, 23, 3)),
    152: (BottleneckBlock, (3, 8, 36, 3)),
}


class ResNet(Layer):
    def __init__(self, depth: int = 50, num_classes: int = 1000,
                 with_pool: bool = True, groups: int = 1,
                 width_per_group: int = 64):
        super().__init__()
        block, layers = _CONFIGS[depth]
        if (groups != 1 or width_per_group != 64) \
                and block is not BottleneckBlock:
            raise ValueError("groups/width_per_group need a bottleneck "
                             "depth (50/101/152)")
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        ch = 64
        stages = []
        for i, (n, width) in enumerate(zip(layers, (64, 128, 256, 512))):
            blocks = []
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                if block is BottleneckBlock:
                    blocks.append(block(ch, width, stride, groups=groups,
                                        base_width=width_per_group))
                else:
                    blocks.append(block(ch, width, stride))
                ch = width * block.expansion
            stages.append(Sequential(*blocks))
        self.layer1, self.layer2, self.layer3, self.layer4 = stages
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def resnet18(num_classes=1000, **kw):
    return ResNet(18, num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(34, num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(50, num_classes=num_classes, **kw)


class LeNet(Layer):
    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, stride=2),
            Conv2D(6, 16, 5, stride=1), ReLU(),
            MaxPool2D(2, stride=2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.reshape(x.shape[0], -1))


# ---------------------------------------------------------------------------
# VGG (reference: python/paddle/vision/models/vgg.py)
# ---------------------------------------------------------------------------

_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    def __init__(self, depth: int = 16, batch_norm: bool = False,
                 num_classes: int = 1000):
        super().__init__()
        layers = []
        c = 3
        for v in _VGG_CFGS[depth]:
            if v == "M":
                layers.append(MaxPool2D(2, 2))
            else:
                layers.append(Conv2D(c, v, 3, padding=1))
                if batch_norm:
                    layers.append(BatchNorm2D(v))
                layers.append(ReLU())
                c = v
        self.features = Sequential(*layers)
        self.num_classes = num_classes
        if num_classes > 0:
            self.avgpool = AdaptiveAvgPool2D(7)
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(0.5),
                Linear(4096, 4096), ReLU(), Dropout(0.5),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.avgpool(x)
            x = x.reshape(x.shape[0], -1)
            x = self.classifier(x)
        return x


def vgg11(batch_norm=False, **kw):
    return VGG(11, batch_norm, **kw)


def vgg13(batch_norm=False, **kw):
    return VGG(13, batch_norm, **kw)


def vgg16(batch_norm=False, **kw):
    return VGG(16, batch_norm, **kw)


def vgg19(batch_norm=False, **kw):
    return VGG(19, batch_norm, **kw)


# ---------------------------------------------------------------------------
# AlexNet (reference: python/paddle/vision/models/alexnet.py)
# ---------------------------------------------------------------------------

class AlexNet(Layer):
    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D(6)
        self.classifier = Sequential(
            Dropout(0.5), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(0.5), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def alexnet(**kw):
    return AlexNet(**kw)


# ---------------------------------------------------------------------------
# SqueezeNet (reference: python/paddle/vision/models/squeezenet.py)
# ---------------------------------------------------------------------------

class _Fire(Layer):
    def __init__(self, c_in, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(c_in, squeeze, 1)
        self.e1 = Conv2D(squeeze, e1, 1)
        self.e3 = Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        import jax.numpy as jnp
        s = F.relu(self.squeeze(x))
        return jnp_concat([F.relu(self.e1(s)), F.relu(self.e3(s))],
                               axis=1)


class SqueezeNet(Layer):
    def __init__(self, version: str = "1.1", num_classes: int = 1000):
        super().__init__()
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(0.5), Conv2D(512, num_classes, 1), ReLU(),
            AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return x.reshape(x.shape[0], -1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


# ---------------------------------------------------------------------------
# MobileNet V1/V2 (reference: python/paddle/vision/models/mobilenetv1.py, v2)
# ---------------------------------------------------------------------------

class _ConvBNRelu(Layer):
    def __init__(self, c_in, c_out, k, stride=1, groups=1, act="relu6"):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(c_out)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu6(x) if self.act == "relu6" else (
            F.relu(x) if self.act == "relu" else x)


class MobileNetV1(Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        def c(ch):
            return max(8, int(ch * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNRelu(3, c(32), 3, stride=2, act="relu")]
        for c_in, c_out, s in cfg:
            layers.append(_ConvBNRelu(c(c_in), c(c_in), 3, stride=s,
                                      groups=c(c_in), act="relu"))
            layers.append(_ConvBNRelu(c(c_in), c(c_out), 1, act="relu"))
        self.features = Sequential(*layers)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(x.reshape(x.shape[0], -1))


class _InvertedResidual(Layer):
    def __init__(self, c_in, c_out, stride, expand):
        super().__init__()
        hidden = int(round(c_in * expand))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand != 1:
            layers.append(_ConvBNRelu(c_in, hidden, 1))
        layers += [
            _ConvBNRelu(hidden, hidden, 3, stride=stride, groups=hidden),
            _ConvBNRelu(hidden, c_out, 1, act="none"),
        ]
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        def c(ch):
            return max(8, int(ch * scale))
        layers = [_ConvBNRelu(3, c(32), 3, stride=2)]
        c_in = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(_InvertedResidual(c_in, c(ch),
                                                s if i == 0 else 1, t))
                c_in = c(ch)
        last = c(1280) if scale > 1.0 else 1280
        layers.append(_ConvBNRelu(c_in, last, 1))
        self.features = Sequential(*layers)
        self.pool = AdaptiveAvgPool2D(1)
        self.classifier = Sequential(Dropout(0.2), Linear(last, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def mobilenet_v1(scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


# ---------------------------------------------------------------------------
# DenseNet (reference: python/paddle/vision/models/densenet.py)
# ---------------------------------------------------------------------------

class _DenseLayer(Layer):
    def __init__(self, c_in, growth, bn_size):
        super().__init__()
        self.bn1 = BatchNorm2D(c_in)
        self.conv1 = Conv2D(c_in, bn_size * growth, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth)
        self.conv2 = Conv2D(bn_size * growth, growth, 3, padding=1,
                            bias_attr=False)

    def forward(self, x):
        import jax.numpy as jnp
        y = self.conv1(F.relu(self.bn1(x)))
        y = self.conv2(F.relu(self.bn2(y)))
        return jnp_concat([x, y], axis=1)


class _Transition(Layer):
    def __init__(self, c_in, c_out):
        super().__init__()
        self.bn = BatchNorm2D(c_in)
        self.conv = Conv2D(c_in, c_out, 1, bias_attr=False)
        self.pool = AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(F.relu(self.bn(x))))


_DENSENET_CFGS = {121: (32, (6, 12, 24, 16)), 161: (48, (6, 12, 36, 24)),
                  169: (32, (6, 12, 32, 32)), 201: (32, (6, 12, 48, 32))}


class DenseNet(Layer):
    def __init__(self, layers: int = 121, bn_size: int = 4,
                 num_classes: int = 1000):
        super().__init__()
        growth, blocks = _DENSENET_CFGS[layers]
        c = 2 * growth
        feats = [Conv2D(3, c, 7, stride=2, padding=3, bias_attr=False),
                 BatchNorm2D(c), ReLU(), MaxPool2D(3, 2, padding=1)]
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size))
                c += growth
            if bi != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [BatchNorm2D(c), ReLU()]
        self.features = Sequential(*feats)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c, num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(x.reshape(x.shape[0], -1))


def densenet121(**kw):
    return DenseNet(121, **kw)


__all__ += ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "AlexNet", "alexnet",
            "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
            "MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2",
            "DenseNet", "densenet121"]


def resnet101(num_classes=1000, **kw):
    return ResNet(101, num_classes=num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(152, num_classes=num_classes, **kw)


def resnext50_32x4d(num_classes=1000, **kw):
    """Reference: paddle.vision.models.resnext50_32x4d."""
    return ResNet(50, num_classes=num_classes, groups=32,
                  width_per_group=4, **kw)


def resnext101_64x4d(num_classes=1000, **kw):
    return ResNet(101, num_classes=num_classes, groups=64,
                  width_per_group=4, **kw)


def wide_resnet50_2(num_classes=1000, **kw):
    """Reference: paddle.vision.models.wide_resnet50_2 (2x-wide 3x3s)."""
    return ResNet(50, num_classes=num_classes, width_per_group=128, **kw)


def wide_resnet101_2(num_classes=1000, **kw):
    return ResNet(101, num_classes=num_classes, width_per_group=128, **kw)


# -- ShuffleNetV2 (reference: paddle/vision/models/shufflenetv2.py) ---------

class _ShuffleUnit(Layer):
    """Stride-1 unit: split channels, transform one half, concat, shuffle.
    Stride-2 unit: both branches transform, spatial down."""

    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        from ..nn.layers_more import ChannelShuffle
        self.stride = stride
        branch_ch = out_ch // 2
        if stride == 1:
            main_in = in_ch // 2
        else:
            main_in = in_ch
            self.branch1 = Sequential(
                Conv2D(in_ch, in_ch, 3, stride=2, padding=1, groups=in_ch,
                       bias_attr=False),
                BatchNorm2D(in_ch),
                Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU())
        self.branch2 = Sequential(
            Conv2D(main_in, branch_ch, 1, bias_attr=False),
            BatchNorm2D(branch_ch), ReLU(),
            Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                   groups=branch_ch, bias_attr=False),
            BatchNorm2D(branch_ch),
            Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
            BatchNorm2D(branch_ch), ReLU())
        self.shuffle = ChannelShuffle(2)

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = jnp_concat([x1, self.branch2(x2)])
        else:
            out = jnp_concat([self.branch1(x), self.branch2(x)])
        return self.shuffle(out)




def jnp_concat(xs, axis=1):
    import jax.numpy as jnp
    return jnp.concatenate(xs, axis=axis)


class ShuffleNetV2(Layer):
    """Reference: paddle.vision.models.ShuffleNetV2."""

    _STAGE_CH = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
                 1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        c2, c3, c4, c5 = self._STAGE_CH[scale]
        self.conv1 = Sequential(Conv2D(3, 24, 3, stride=2, padding=1,
                                       bias_attr=False),
                                BatchNorm2D(24), ReLU())
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        ch = 24
        stages = []
        for out_ch, repeat in zip((c2, c3, c4), (4, 8, 4)):
            units = [_ShuffleUnit(ch, out_ch, 2)]
            units += [_ShuffleUnit(out_ch, out_ch, 1)
                      for _ in range(repeat - 1)]
            stages.append(Sequential(*units))
            ch = out_ch
        self.stage2, self.stage3, self.stage4 = stages
        self.conv5 = Sequential(Conv2D(ch, c5, 1, bias_attr=False),
                                BatchNorm2D(c5), ReLU())
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(c5, num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv5(self.stage4(self.stage3(self.stage2(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(scale=2.0, **kw)


# -- GoogLeNet (reference: paddle/vision/models/googlenet.py) ---------------

class _Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        def cbr(i, o, k, p=0):
            return Sequential(Conv2D(i, o, k, padding=p, bias_attr=False),
                              BatchNorm2D(o), ReLU())
        self.b1 = cbr(in_ch, c1, 1)
        self.b2 = Sequential(cbr(in_ch, c3r, 1), cbr(c3r, c3, 3, 1))
        self.b3 = Sequential(cbr(in_ch, c5r, 1), cbr(c5r, c5, 5, 2))
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             cbr(in_ch, pool_proj, 1))

    def forward(self, x):
        return jnp_concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)])


class GoogLeNet(Layer):
    """Inception v1 (reference: paddle.vision.models.GoogLeNet); the aux
    classifiers are train-time-only in the reference and omitted here
    (documented deviation — the backbone/logits match)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        def cbr(i, o, k, s=1, p=0):
            return Sequential(Conv2D(i, o, k, stride=s, padding=p,
                                     bias_attr=False),
                              BatchNorm2D(o), ReLU())
        self.stem = Sequential(
            cbr(3, 64, 7, 2, 3), MaxPool2D(3, stride=2, padding=1),
            cbr(64, 64, 1), cbr(64, 192, 3, 1, 1),
            MaxPool2D(3, stride=2, padding=1))
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, stride=2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, stride=2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4e(self.inc4d(self.inc4c(self.inc4b(self.inc4a(x)))))
        x = self.inc5b(self.inc5a(self.pool4(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.reshape(x.shape[0], -1)))
        return x


def googlenet(**kw):
    return GoogLeNet(**kw)


__all__ += [
    "resnet101", "resnet152", "resnext50_32x4d", "resnext101_64x4d",
    "wide_resnet50_2", "wide_resnet101_2",
    "ShuffleNetV2", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
    "GoogLeNet", "googlenet",
]


# ---------------------------------------------------------------------------
# MobileNetV3 (reference: python/paddle/vision/models/mobilenetv3.py)
# ---------------------------------------------------------------------------

def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.fc1 = Conv2D(ch, mid, 1)
        self.fc2 = Conv2D(mid, ch, 1)

    def forward(self, x):
        s = x.mean(axis=(2, 3), keepdims=True)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MNV3Block(Layer):
    def __init__(self, c_in, c_mid, c_out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        self.expand = (_ConvBNRelu(c_in, c_mid, 1, act="none")
                       if c_mid != c_in else None)
        self.dw = _ConvBNRelu(c_mid, c_mid, k, stride=stride, groups=c_mid,
                              act="none")
        self.se = _SqueezeExcite(c_mid) if use_se else None
        self.project = _ConvBNRelu(c_mid, c_out, 1, act="none")
        self.act = act

    def _a(self, x):
        return F.hardswish(x) if self.act == "hardswish" else F.relu(x)

    def forward(self, x):
        out = x
        if self.expand is not None:
            out = self._a(self.expand(out))
        out = self._a(self.dw(out))
        if self.se is not None:
            out = self.se(out)
        out = self.project(out)
        return x + out if self.use_res else out


_MNV3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_MNV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(Layer):
    """Reference: paddle MobileNetV3Large/Small (Howard 2019)."""

    def __init__(self, config="large", scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = _MNV3_LARGE if config == "large" else _MNV3_SMALL
        last_mid = 960 if config == "large" else 576
        last_ch = 1280 if config == "large" else 1024
        c = lambda ch: _make_divisible(ch * scale)
        self.stem = _ConvBNRelu(3, c(16), 3, stride=2, act="none")
        blocks = []
        c_in = c(16)
        for k, exp, out, se, act, stride in cfg:
            blocks.append(_MNV3Block(c_in, c(exp), c(out), k, stride, se,
                                     act))
            c_in = c(out)
        self.blocks = Sequential(*blocks)
        self.last_conv = _ConvBNRelu(c_in, c(last_mid), 1, act="none")
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.head = Sequential(Linear(c(last_mid), last_ch),
                                   Linear(last_ch, num_classes))

    def forward(self, x):
        x = F.hardswish(self.stem(x))
        x = self.blocks(x)
        x = F.hardswish(self.last_conv(x))
        if self.with_pool:
            x = x.mean(axis=(2, 3))
        if self.num_classes > 0:
            x = self.head[0](x)
            x = F.hardswish(x)
            x = self.head[1](x)
        return x


def mobilenet_v3_large(scale=1.0, **kw):
    return MobileNetV3("large", scale=scale, **kw)


def mobilenet_v3_small(scale=1.0, **kw):
    return MobileNetV3("small", scale=scale, **kw)


# ---------------------------------------------------------------------------
# InceptionV3 (reference: python/paddle/vision/models/inceptionv3.py)
# ---------------------------------------------------------------------------

class _IncConv(Layer):
    def __init__(self, c_in, c_out, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(c_out)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class _InceptionA(Layer):
    def __init__(self, c_in, pool_features):
        super().__init__()
        self.b1 = _IncConv(c_in, 64, 1)
        self.b5 = Sequential(_IncConv(c_in, 48, 1),
                             _IncConv(48, 64, 5, padding=2))
        self.b3 = Sequential(_IncConv(c_in, 64, 1),
                             _IncConv(64, 96, 3, padding=1),
                             _IncConv(96, 96, 3, padding=1))
        self.bp = _IncConv(c_in, pool_features, 1)

    def forward(self, x):
        pool = F.avg_pool2d(F.pad(x, [1, 1, 1, 1]), 3, stride=1)
        return jnp_concat(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(pool)], axis=1)


class _InceptionB(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b3 = _IncConv(c_in, 384, 3, stride=2)
        self.b3d = Sequential(_IncConv(c_in, 64, 1),
                              _IncConv(64, 96, 3, padding=1),
                              _IncConv(96, 96, 3, stride=2))

    def forward(self, x):
        pool = F.max_pool2d(x, 3, stride=2)
        return jnp_concat([self.b3(x), self.b3d(x), pool], axis=1)


class _InceptionC(Layer):
    def __init__(self, c_in, c7):
        super().__init__()
        self.b1 = _IncConv(c_in, 192, 1)
        self.b7 = Sequential(_IncConv(c_in, c7, 1),
                             _IncConv(c7, c7, (1, 7), padding=(0, 3)),
                             _IncConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(_IncConv(c_in, c7, 1),
                              _IncConv(c7, c7, (7, 1), padding=(3, 0)),
                              _IncConv(c7, c7, (1, 7), padding=(0, 3)),
                              _IncConv(c7, c7, (7, 1), padding=(3, 0)),
                              _IncConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = _IncConv(c_in, 192, 1)

    def forward(self, x):
        pool = F.avg_pool2d(F.pad(x, [1, 1, 1, 1]), 3, stride=1)
        return jnp_concat(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(pool)], axis=1)


class _InceptionD(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b3 = Sequential(_IncConv(c_in, 192, 1),
                             _IncConv(192, 320, 3, stride=2))
        self.b7 = Sequential(_IncConv(c_in, 192, 1),
                             _IncConv(192, 192, (1, 7), padding=(0, 3)),
                             _IncConv(192, 192, (7, 1), padding=(3, 0)),
                             _IncConv(192, 192, 3, stride=2))

    def forward(self, x):
        pool = F.max_pool2d(x, 3, stride=2)
        return jnp_concat([self.b3(x), self.b7(x), pool], axis=1)


class _InceptionE(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b1 = _IncConv(c_in, 320, 1)
        self.b3_stem = _IncConv(c_in, 384, 1)
        self.b3_a = _IncConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _IncConv(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = Sequential(_IncConv(c_in, 448, 1),
                                  _IncConv(448, 384, 3, padding=1))
        self.bd_a = _IncConv(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _IncConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = _IncConv(c_in, 192, 1)

    def forward(self, x):
        s3 = self.b3_stem(x)
        sd = self.bd_stem(x)
        pool = F.avg_pool2d(F.pad(x, [1, 1, 1, 1]), 3, stride=1)
        return jnp_concat(
            [self.b1(x), self.b3_a(s3), self.b3_b(s3),
             self.bd_a(sd), self.bd_b(sd), self.bp(pool)], axis=1)


class InceptionV3(Layer):
    """Reference: paddle.vision.models.InceptionV3 (299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            _IncConv(3, 32, 3, stride=2), _IncConv(32, 32, 3),
            _IncConv(32, 64, 3, padding=1))
        self.stem2 = Sequential(_IncConv(64, 80, 1),
                                _IncConv(80, 192, 3))
        self.blocks = Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = F.max_pool2d(x, 3, stride=2)
        x = self.stem2(x)
        x = F.max_pool2d(x, 3, stride=2)
        x = self.blocks(x)
        if self.with_pool:
            x = x.mean(axis=(2, 3))
        if self.num_classes > 0:
            x = self.fc(x)
        return x


def inception_v3(**kw):
    return InceptionV3(**kw)


def lenet(num_classes=10):
    """Reference: paddle.vision.models.LeNet factory."""
    return LeNet(num_classes=num_classes)


__all__ += ["MobileNetV3", "mobilenet_v3_large", "mobilenet_v3_small",
            "InceptionV3", "inception_v3", "lenet"]


def densenet161(**kw):
    return DenseNet(161, **kw)


def densenet169(**kw):
    return DenseNet(169, **kw)


def densenet201(**kw):
    return DenseNet(201, **kw)


__all__ += ["densenet161", "densenet169", "densenet201"]
