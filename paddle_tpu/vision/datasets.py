"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress image: no downloads. MNIST/Cifar load from pre-downloaded
files when given a path; RandomDataset provides the test/CI data source
(the reference's fake_data pattern).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "Cifar10", "RandomDataset"]


class RandomDataset(Dataset):
    """Deterministic random images + labels (CI/test data source)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform: Optional[Callable] = None,
                 seed=0):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        r = np.random.default_rng(self.seed * 1_000_003 + idx)
        img = r.normal(size=self.shape).astype("float32")
        label = np.int64(r.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """idx-format MNIST from local files (no download — zero egress)."""

    def __init__(self, image_path: str, label_path: str, mode="train",
                 transform: Optional[Callable] = None):
        self.transform = transform
        with (gzip.open(image_path, "rb") if image_path.endswith(".gz")
              else open(image_path, "rb")) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with (gzip.open(label_path, "rb") if label_path.endswith(".gz")
              else open(label_path, "rb")) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            self.labels = np.frombuffer(f.read(), np.uint8).astype("int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(Dataset):
    """CIFAR-10 python pickle batches from a local directory."""

    def __init__(self, data_dir: str, mode="train",
                 transform: Optional[Callable] = None):
        self.transform = transform
        files = ([f"data_batch_{i}" for i in range(1, 6)]
                 if mode == "train" else ["test_batch"])
        xs, ys = [], []
        for fn in files:
            with open(os.path.join(data_dir, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, "int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    """Same idx wire format as MNIST (reference:
    python/paddle/vision/datasets/mnist.py FashionMNIST subclass)."""


class Cifar100(Dataset):
    """CIFAR-100 python pickle (train/test files, fine labels)."""

    def __init__(self, data_dir: str, mode="train",
                 transform: Optional[Callable] = None):
        self.transform = transform
        fn = "train" if mode == "train" else "test"
        with open(os.path.join(data_dir, fn), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        self.images = d[b"data"].reshape(-1, 3, 32, 32)
        self.labels = np.asarray(d[b"fine_labels"], "int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return np.asarray(Image.open(f).convert("RGB"))


class DatasetFolder(Dataset):
    """Class-per-subfolder sample tree (reference:
    python/paddle/vision/datasets/folder.py): root/<class>/<file>."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or
                     (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(
                f"DatasetFolder: no class subfolders under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, names in sorted(os.walk(cdir)):
                for n in sorted(names):
                    p = os.path.join(base, n)
                    ok = (is_valid_file(p) if is_valid_file
                          else n.lower().endswith(exts))
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class ImageFolder(DatasetFolder):
    """Flat image list (labels ignored — reference ImageFolder yields
    images only); also accepts the class-tree layout."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        exts = tuple(e.lower() for e in (extensions or
                     (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")))
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        self.samples = []
        for base, _, names in sorted(os.walk(root)):
            for n in sorted(names):
                p = os.path.join(base, n)
                ok = (is_valid_file(p) if is_valid_file
                      else n.lower().endswith(exts))
                if ok:
                    self.samples.append(p)
        if not self.samples:
            raise FileNotFoundError(f"ImageFolder: no images under {root!r}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)


class Flowers(Dataset):
    """Oxford-102 flowers from a local extracted layout: jpg/ images +
    imagelabels.mat + setid.mat (reference:
    python/paddle/vision/datasets/flowers.py; downloads disabled)."""

    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_dir, mode="train", transform=None):
        from scipy.io import loadmat
        labels = loadmat(os.path.join(data_dir, "imagelabels.mat"))
        setid = loadmat(os.path.join(data_dir, "setid.mat"))
        ids = setid[self._SPLIT_KEY[mode]].reshape(-1)
        self.files = [os.path.join(data_dir, "jpg",
                                   f"image_{i:05d}.jpg") for i in ids]
        self.labels = labels["labels"].reshape(-1)[ids - 1].astype("int64") - 1
        self.transform = transform

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        img = _pil_loader(self.files[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation pairs from the extracted VOCdevkit
    (reference: python/paddle/vision/datasets/voc2012.py)."""

    def __init__(self, data_dir, mode="train", transform=None):
        base = os.path.join(data_dir, "VOC2012") \
            if os.path.isdir(os.path.join(data_dir, "VOC2012")) else data_dir
        split_file = os.path.join(base, "ImageSets", "Segmentation",
                                  ("train.txt" if mode == "train" else
                                   "val.txt"))
        with open(split_file) as f:
            names = [l.strip() for l in f if l.strip()]
        self.images = [os.path.join(base, "JPEGImages", n + ".jpg")
                       for n in names]
        self.masks = [os.path.join(base, "SegmentationClass", n + ".png")
                      for n in names]
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        from PIL import Image
        img = _pil_loader(self.images[idx])
        with open(self.masks[idx], "rb") as f:
            mask = np.asarray(Image.open(f))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask
