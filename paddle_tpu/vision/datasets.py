"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress image: no downloads. MNIST/Cifar load from pre-downloaded
files when given a path; RandomDataset provides the test/CI data source
(the reference's fake_data pattern).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "Cifar10", "RandomDataset"]


class RandomDataset(Dataset):
    """Deterministic random images + labels (CI/test data source)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform: Optional[Callable] = None,
                 seed=0):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        r = np.random.default_rng(self.seed * 1_000_003 + idx)
        img = r.normal(size=self.shape).astype("float32")
        label = np.int64(r.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """idx-format MNIST from local files (no download — zero egress)."""

    def __init__(self, image_path: str, label_path: str, mode="train",
                 transform: Optional[Callable] = None):
        self.transform = transform
        with (gzip.open(image_path, "rb") if image_path.endswith(".gz")
              else open(image_path, "rb")) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with (gzip.open(label_path, "rb") if label_path.endswith(".gz")
              else open(label_path, "rb")) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            self.labels = np.frombuffer(f.read(), np.uint8).astype("int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(Dataset):
    """CIFAR-10 python pickle batches from a local directory."""

    def __init__(self, data_dir: str, mode="train",
                 transform: Optional[Callable] = None):
        self.transform = transform
        files = ([f"data_batch_{i}" for i in range(1, 6)]
                 if mode == "train" else ["test_batch"])
        xs, ys = [], []
        for fn in files:
            with open(os.path.join(data_dir, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, "int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]
