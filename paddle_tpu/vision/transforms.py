"""Vision transforms (reference: python/paddle/vision/transforms/).

NumPy/host-side, HWC uint8/float input (what a DataLoader worker sees),
matching the reference's functional semantics for the common subset.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["BaseTransform", "Compose", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "Normalize", "ToTensor", "Transpose"]


class BaseTransform:
    """Reference: paddle.vision.transforms.BaseTransform — dispatch a
    transform over typed inputs (image/coords/boxes/mask) declared by
    ``keys``; subclasses override ``_get_params`` and the ``_apply_*``
    hooks.  Single-input subclasses only override ``_apply_image``."""

    def __init__(self, keys=None):
        self.keys = tuple(keys) if keys else ("image",)
        self.params = None

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        return image

    def _apply_coords(self, coords):
        return coords

    def _apply_boxes(self, boxes):
        return boxes

    def _apply_mask(self, mask):
        return mask

    def __call__(self, inputs):
        single = not isinstance(inputs, (list, tuple))
        items = (inputs,) if single else tuple(inputs)
        self.params = self._get_params(items)
        outs = []
        for key, item in zip(self.keys, items):
            apply = getattr(self, f"_apply_{key}", None)
            outs.append(apply(item) if apply else item)
        return outs[0] if single else tuple(outs)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


def _resize_np(img: np.ndarray, size) -> np.ndarray:
    """Bilinear resize without external deps (HWC)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        # reference semantics: shorter edge → size, keep aspect
        if h < w:
            oh, ow = size, max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), size
    else:
        oh, ow = size
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img_f = img.astype(np.float32)
    if img_f.ndim == 2:
        img_f = img_f[:, :, None]
        squeeze = True
    else:
        squeeze = False
    out = ((img_f[y0][:, x0] * (1 - wy) * (1 - wx))
           + (img_f[y0][:, x1] * (1 - wy) * wx)
           + (img_f[y1][:, x0] * wy * (1 - wx))
           + (img_f[y1][:, x1] * wy * wx))
    if squeeze:
        out = out[:, :, 0]
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


class Resize:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, rng: Optional[np.random.Generator] = None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = int(self.rng.integers(0, max(1, h - th + 1)))
        j = int(self.rng.integers(0, max(1, w - tw + 1)))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5,
                 rng: Optional[np.random.Generator] = None):
        self.prob = prob
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        if self.rng.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Normalize:
    """(x - mean) / std per channel. data_format CHW (post-ToTensor) or HWC."""

    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        out = img.transpose(2, 0, 1).astype(np.float32)
        if np.asarray(img).dtype == np.uint8:
            out = out / 255.0
        return out


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


# round-3 tail (functional API + random/color/geometric transforms) —
# see transforms_tail3.py
from .transforms_tail3 import *  # noqa: E402,F401,F403
from . import transforms_tail3 as _t3  # noqa: E402

__all__ += _t3.__all__
