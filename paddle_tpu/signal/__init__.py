"""``paddle.signal`` parity: stft / istft.

Reference: python/paddle/signal.py (stft, istft over the fft ops).
stft is shared with ``paddle_tpu.audio``; istft is the overlap-add
inverse with window-envelope normalization (NOLA), trace-compatible.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..audio import get_window, stft  # noqa: F401  (stft re-exported)

__all__ = ["stft", "istft"]


def istft(x, n_fft=512, hop_length=None, win_length=None, window="hann",
          center=True, length=None):
    """Inverse of :func:`stft`. x: complex (..., n_fft//2+1, frames) →
    real (..., T)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    win = get_window(window, wl)
    if wl < n_fft:
        pad = (n_fft - wl) // 2
        win = jnp.pad(win, (pad, n_fft - wl - pad))
    frames = jnp.fft.irfft(jnp.swapaxes(x, -1, -2), n=n_fft, axis=-1)
    frames = frames * win                       # (..., n_frames, n_fft)
    n_frames = frames.shape[-2]
    t_full = n_fft + hop * (n_frames - 1)
    # overlap-add via scatter
    out = jnp.zeros(frames.shape[:-2] + (t_full,), frames.dtype)
    env = jnp.zeros((t_full,), frames.dtype)
    win_sq = win * win
    for f in range(n_frames):  # unrolled: n_frames is static under jit
        sl = slice(f * hop, f * hop + n_fft)
        out = out.at[..., sl].add(frames[..., f, :])
        env = env.at[sl].add(win_sq)
    out = out / jnp.maximum(env, 1e-11)
    if center:
        out = out[..., n_fft // 2: t_full - n_fft // 2]
    if length is not None:
        out = out[..., :length]
        if out.shape[-1] < length:
            pad_cfg = [(0, 0)] * (out.ndim - 1) + [(0, length - out.shape[-1])]
            out = jnp.pad(out, pad_cfg)
    return out
