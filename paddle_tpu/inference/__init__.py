"""Inference API (reference: paddle/fluid/inference/ AnalysisPredictor,
paddle_infer.Config/create_predictor — SURVEY §2.3).

TPU redesign: the reference's analysis passes + TensorRT subgraphs are
XLA's job; a "predictor" here is an AOT-compiled XLA program. Two paths:

- from a live Layer: ``Config(model=layer, example_args=...)`` — jit once,
  optionally donate/convert dtypes;
- from a ``paddle_tpu.jit.save`` artifact: ``Config(model_path=...)`` —
  deserialize StableHLO and run without the Python model definition
  (the *.pdmodel-file role).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax


class Config:
    """Mirror of paddle_infer.Config's role (model source + exec options)."""

    def __init__(self, model=None, model_path: Optional[str] = None,
                 example_args: Optional[Sequence[Any]] = None,
                 params: Optional[dict] = None):
        if (model is None) == (model_path is None):
            raise ValueError("pass exactly one of model / model_path")
        self.model = model
        self.model_path = model_path
        self.example_args = example_args
        self.params = params


class Predictor:
    """paddle_infer.Predictor parity: run() over named/positional inputs.

    ``run()`` executes through a real AOT executable: the first call (or
    an explicit :meth:`warmup`) does ``jit.lower(*args).compile()`` —
    the reference's analysis/optimization-pass moment — and subsequent
    calls dispatch the compiled artifact directly.  ``__call__`` keeps
    the plain jit path (trace-compatible, e.g. under vmap/grad)."""

    def __init__(self, config: Config):
        self._config = config
        if config.model_path is not None:
            from .. import jit as pjit
            self._fn = jax.jit(pjit.load(config.model_path))
        else:
            model = config.model
            from ..nn.layer import Layer, functional_call, serving_params
            if isinstance(model, Layer):
                model.eval()
                params = config.params or serving_params(model)

                def fn(*args):
                    return functional_call(model, params, *args,
                                           training=False)
                self._fn = jax.jit(fn)
            else:
                self._fn = jax.jit(model)
        self._compiled = None
        self._compiled_key = None
        self._executables = {}   # arg_key -> compiled executable

    @staticmethod
    def _arg_key(args):
        # the treedef matters, not just the leaves: run(x, y) and
        # run((x, y)) flatten to the same leaves but need different
        # executables (an AOT artifact is fixed to one call structure)
        leaves, treedef = jax.tree.flatten(list(args))
        return (treedef, tuple(
            (tuple(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", type(leaf).__name__)))
            for leaf in leaves))

    def warmup(self, *example_args) -> "Predictor":
        """AOT-compile for the given (or ``Config.example_args``) input
        shapes; ``run()`` then dispatches the compiled executable."""
        args = example_args or tuple(self._config.example_args or ())
        if not args:
            raise ValueError(
                "warmup() needs example inputs: pass them here or in "
                "Config(example_args=...)")
        key = self._arg_key(args)
        compiled = self._executables.get(key)
        if compiled is None:
            compiled = self._fn.lower(*args).compile()
            # recorded only after a SUCCESSFUL compile: a raising
            # lower/compile must not leave a stale executable keyed to
            # the new geometry
            self._executables[key] = compiled
        self._compiled = compiled
        self._compiled_key = key
        return self

    def run(self, *inputs):
        # AOT memo per input geometry (like the jit cache it replaces):
        # a NEW geometry lowers+compiles once, alternating geometries
        # dispatch their recorded executables
        if self._compiled is None or self._arg_key(inputs) != \
                self._compiled_key:
            self.warmup(*inputs)
        out = self._compiled(*inputs)
        return jax.tree.leaves(out) if not isinstance(out, (list, tuple)) \
            else list(out)

    def __call__(self, *inputs):
        return self._fn(*inputs)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
