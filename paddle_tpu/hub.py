"""paddle_tpu.hub — model hub (local-source protocol).

Reference: python/paddle/hub.py (help/list/load over a repo's
``hubconf.py`` entrypoints).  The ``local`` / ``dir`` source is fully
implemented; ``github``/``gitee`` sources need network egress, which
this environment forbids — clone the repo and point ``source='local'``
at it.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["help", "list", "load"]

_builtin_list = list


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"hub: no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location(
        f"pdtpu_hubconf_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir, source):
    if source in ("local", "dir"):
        return repo_dir
    raise NotImplementedError(
        f"hub source {source!r} needs network egress (disabled here): "
        "clone the repo locally and pass source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    deps = getattr(mod, "dependencies", [])
    del deps
    return sorted(n for n in dir(mod)
                  if callable(getattr(mod, n)) and not n.startswith("_"))


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hub: no entrypoint {model!r}; available: "
                         f"{list(repo_dir, source)}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate one entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hub: no entrypoint {model!r}; available: "
                         f"{list(repo_dir, source)}")
    return fn(**kwargs)
