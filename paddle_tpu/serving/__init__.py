"""``paddle_tpu.serving`` — continuous-batching inference on the paged
KV cache (docs/SERVING.md).

The reference stack serves through PaddleNLP's inference engine over the
fused decode kernels; here the serving tier is TPU-native: one global
paged KV pool per layer with hash-based prefix sharing (refcounted
copy-on-write blocks, LRU eviction), a fixed-slot scheduler so the WHOLE
serving step — chunked prefill spans and decode tokens in one ragged
batch — compiles exactly once, and the ragged paged-attention Pallas
kernel (``ops/pallas/ragged_attention.py``) doing the reads.

Production front door (docs/SERVING.md "Front door"): ``FrontDoor``
layers multi-tenant SLO admission on the Engine — per-tenant
token-bucket rate limits and quotas, priority + deficit-round-robin
fairness, telemetry-driven load shedding with typed retry-after
answers, and KV-block preemption (``SwapManager`` pages victims to host
RAM) instead of rejection; ``ServingServer`` is the stdlib streaming
HTTP process over it, with graceful SIGTERM drain.  Admission failures
are typed (``errors.AdmissionError`` and friends, all ``ValueError``
subclasses).

Sharded serving (docs/SERVING.md "Sharded serving"):
``Engine(mesh=serving_mesh(tp))`` TP-partitions one engine over the
mesh (params by partition spec, paged pools head-sharded over ``mp`` —
token-identical to single-chip, zero-recompile contract intact);
``EngineReplicaSet`` runs N engines on disjoint submeshes
(``replica_meshes``) behind the same FrontDoor with prefix-affinity +
least-loaded routing and replica-failure evacuation through the
preempt→restore path.

Disaggregated serving (docs/SERVING.md "Disaggregated serving"):
``DisaggReplicaSet`` splits the fleet into ``Engine(role="prefill")``
replicas (retire at prefill-complete: first token emitted, pages
swapped out) and ``Engine(role="decode")`` replicas that resume from a
transferred ``KVHandout`` — pages stream over a ``KVTransport``
(in-process ``LoopbackTransport``, or ``StoreTransport`` over the
TCPStore for multi-host) with chunked crc-verified, retried I/O — so
TTFT and aggregate tok/s scale on independent axes behind the same
FrontDoor.

Batched multi-LoRA (docs/SERVING.md "Multi-LoRA"):
``Engine(lora=LoRAPool(model, ...))`` serves many fine-tuned adapters
from ONE engine — stacked low-rank weight pools ride the compiled step
as fixed-shape inputs, per-slot adapter ids are batch data (mixed
tenants in one ragged dispatch through the grouped BGMV), adapter
load/evict is a buffer write (zero recompiles), and ``FrontDoor`` maps
tenants to adapters via ``TenantPolicy(adapter=)``.  Admission of an
unloaded adapter raises the typed ``errors.UnknownAdapter``; evicting
an adapter with live requests raises ``errors.AdapterInUse``.

Cluster serving (docs/SERVING.md "Cluster serving"): per-host
``ServingWorker`` loops (``python -m paddle_tpu.serving.worker``)
register with the TCPStore under epoch-fenced leases and step their
local Engine independently; a thin ``ClusterController`` routes
admissions/handoffs through store-backed queues, evacuates dead or
draining workers' requests from their last ``KVHandout`` snapshots,
and drives SLO-based elasticity (``role_flip`` / ``drain`` /
``rolling_upgrade``, plus ``WorkerSpawner`` scale-up/scale-down) — no
shared driver, zero recompiles across membership churn.  The
controller itself is as killable as the workers: ``submit`` journals
every admission durably before returning, a standby under
``ControllerLease`` takes over on lease staleness and replays the
journal, and ``ClusterGateway`` is the HTTP front door over it all
(SSE off fenced output records, ``Idempotency-Key`` dedupe, typed
shed, graceful drain).

Usage::

    from paddle_tpu import serving
    eng = serving.Engine(model, max_batch=8, max_seq_len=512).warmup()
    rid = eng.add_request(prompt_ids, max_new_tokens=64)
    for ev in eng.stream():
        ...  # ev.token_id as it decodes

    door = serving.FrontDoor(eng, policies={
        "paid": serving.TenantPolicy(priority=1),
        "free": serving.TenantPolicy(rate_tokens_per_s=500)})
    adm = door.submit(prompt_ids, tenant="free", max_new_tokens=64)
    if not adm.admitted:
        ...  # adm.reason, adm.retry_after_s — typed, not an exception
    serving.ServingServer(door, port=8000).serve_forever()
"""

from __future__ import annotations

from .block_allocator import (BlockAllocator, PagedKVCache,  # noqa: F401
                              PrefixCache, SwapManager)
from .cluster import (ClusterController, ControllerLease,  # noqa: F401
                      LeaseLost, LeaseMonitor, StoreQueue,
                      WorkerSpawner)
from .disagg import (DisaggReplicaSet, HeartbeatMonitor,  # noqa: F401
                     KVHandout, KVTransport, LoopbackTransport,
                     StoreTransport, TransferError)
from .distributed import (EngineReplicaSet, replica_meshes,  # noqa: F401
                          serving_mesh)
from .engine import Engine, TokenEvent  # noqa: F401
from .errors import (AdapterInUse, AdmissionError,  # noqa: F401
                     BudgetUnsatisfiable, QueueFull, RateLimited,
                     UnknownAdapter)
from .lora import LoRAPool, merge_adapter, random_adapter  # noqa: F401
from .frontdoor import (Admission, FrontDoor, TenantPolicy,  # noqa: F401
                        TokenBucket)
from .gateway import ClusterGateway  # noqa: F401
from .scheduler import Request, RequestState, Scheduler  # noqa: F401
from .server import ServingServer  # noqa: F401
from .spec import NgramProposer  # noqa: F401
from .worker import ServingWorker  # noqa: F401

# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
