"""``paddle_tpu.serving`` — continuous-batching inference on the paged
KV cache (docs/SERVING.md).

The reference stack serves through PaddleNLP's inference engine over the
fused decode kernels; here the serving tier is TPU-native: one global
paged KV pool per layer with hash-based prefix sharing (refcounted
copy-on-write blocks, LRU eviction), a fixed-slot scheduler so the WHOLE
serving step — chunked prefill spans and decode tokens in one ragged
batch — compiles exactly once, and the ragged paged-attention Pallas
kernel (``ops/pallas/ragged_attention.py``) doing the reads.

Usage::

    from paddle_tpu import serving
    eng = serving.Engine(model, max_batch=8, max_seq_len=512).warmup()
    rid = eng.add_request(prompt_ids, max_new_tokens=64)
    for ev in eng.stream():
        ...  # ev.token_id as it decodes
"""

from __future__ import annotations

from .block_allocator import (BlockAllocator, PagedKVCache,  # noqa: F401
                              PrefixCache)
from .engine import Engine, TokenEvent  # noqa: F401
from .scheduler import Request, RequestState, Scheduler  # noqa: F401

# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
