"""Batched multi-LoRA serving: stacked adapter pools for the one
compiled step (docs/SERVING.md "Multi-LoRA").

The tenancy problem this solves (ROADMAP item 4a): without it, every
fine-tuned tenant model needs its OWN engine — N adapters means N full
weight copies, N compiled programs, N half-empty batches.  With it, one
engine holds every adapter's low-rank deltas STACKED along a leading
adapter axis — per LoRA-targeted projection ``p`` of every decoder
layer, ``a[p]`` is ``(num_adapters+1, d_in, r)`` and ``b[p]`` is
``(num_adapters+1, r, d_out)`` — and each batch slot carries its
adapter INDEX as per-slot data (``scheduler.span_arrays``), so a mixed
batch of tenants rides the same compiled ``(B, C)`` ragged step the
base model uses.  The grouped BGMV (``incubate.nn.functional.lora_bgmv``
→ ``ops/pallas/lora_matmul.py`` on TPU) gathers each slot's ``A_i``/
``B_i`` by that index and adds ``x @ A_i @ B_i`` to the base
projection.

Zero-recompile contract: the stacks are jit INPUTS of fixed shape, so
loading or evicting an adapter is a buffer write (host mirror edit +
``device_put``) — never a retrace.  Slot 0 is reserved as the EXACT
no-op (all-zero ``A``/``B``): a base-model request contributes
``x @ 0 @ 0 == 0.0`` and its outputs stay bitwise identical to a
LoRA-less engine; on TPU the kernel skips slot-0 rows outright.

Lifecycle: adapters are registered by NAME (``load``), mapped to slots
on a free list, and refcounted by the LIVE REQUEST IDS using them
(``acquire``/``release`` — the Engine calls these at admission and
retirement; request-id keyed, so the preempt→restore, DP-migration and
disagg-handoff paths can re-acquire idempotently).  ``evict`` of a
referenced adapter raises the typed :class:`errors.AdapterInUse`
instead of repointing live slots at garbage.  ``alpha / rank`` is
folded into ``B`` at load time, so the serving delta is a plain
two-matmul chain and the merged-weight reference is
``W + A @ (B * alpha/r)`` (:func:`merge_adapter`).

One pool may back several engines (a DP replica set MUST share one —
slot indices ride ``Request.adapter_slot`` across migration); the
device arrays are plain jit inputs, so sharing is safe.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax.numpy as jnp

from .. import observability as obs
from .errors import AdapterInUse, UnknownAdapter

__all__ = ["LoRAPool", "merge_adapter", "random_adapter"]


def _decoder_layers(model) -> list:
    """The decoder-layer list of a paged-serving CausalLM (Llama's
    ``model.layers`` / GPT's ``model.h``), RecomputeWrapper unwrapped."""
    from ..distributed.recompute import RecomputeWrapper
    mdl = getattr(model, "model", None)
    if mdl is None:
        raise ValueError(
            f"{type(model).__name__} is not a CausalLM (no .model)")
    for attr in ("layers", "h"):
        ll = getattr(mdl, attr, None)
        if ll is not None and hasattr(ll, "__iter__"):
            return [l.inner if isinstance(l, RecomputeWrapper) else l
                    for l in ll]
    raise ValueError(
        f"{type(mdl).__name__} has no decoder-layer list "
        "(expected .layers or .h)")


def _targets(layer) -> Dict[str, Tuple[int, int]]:
    """LoRA-targeted projections of one decoder layer: every 2-D weight
    parameter (q/k/v/o + gate/up/down on Llama; qkv/out + fc_in/fc_out
    on GPT — norms and biases are 1-D and excluded), keyed by its
    dotted path minus ``.weight`` — the same key the model forwards
    index the per-layer pack by."""
    out = {}
    for path, p in layer.named_parameters():
        if path.endswith(".weight") and getattr(p, "ndim", 0) == 2:
            out[path[:-len(".weight")]] = (int(p.shape[0]),
                                           int(p.shape[1]))
    if not out:
        raise ValueError(
            f"{type(layer).__name__} exposes no 2-D projection weights "
            "to target (is the model already weight-quantized? build "
            "the LoRAPool BEFORE Engine(weight_quant=...))")
    return out


class LoRAPool:
    """Stacked multi-adapter LoRA weights for one model geometry.

    ``max_adapters`` named adapters can be resident at once (slot 0 is
    the reserved base no-op on top of that).  ``rank`` is the shared
    LoRA rank r; ``alpha`` the scaling numerator (default ``rank``, i.e.
    scale 1.0) folded into ``B`` at load.  ``dtype`` defaults to the
    model's config dtype — the stacks are cast there on device upload,
    matching what the projections compute in.

    The HOST mirror (float32 numpy) is authoritative; ``device_stacks``
    lazily uploads and caches the jit-input pytree, invalidated by
    ``load``/``evict``.  Uploads are ``device_put`` only — no program
    ever compiles on adapter churn (the serving-smoke gate's multi-LoRA
    pass pins this).
    """

    def __init__(self, model, *, max_adapters: int = 8, rank: int = 8,
                 alpha: Optional[float] = None, dtype=None):
        if max_adapters < 1:
            raise ValueError(f"max_adapters must be >= 1, got "
                             f"{max_adapters}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        layers = _decoder_layers(model)
        self.targets = _targets(layers[0])
        for i, l in enumerate(layers[1:], 1):
            if _targets(l) != self.targets:
                raise ValueError(
                    f"decoder layer {i} exposes different projections "
                    "than layer 0 — heterogeneous stacks are not "
                    "supported")
        self.num_layers = len(layers)
        self.max_adapters = int(max_adapters)
        self.rank = int(rank)
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.dtype = dtype if dtype is not None else \
            getattr(model.cfg, "dtype", "float32")
        n = self.max_adapters + 1      # +1: slot 0 = exact no-op
        # host mirror: per layer, per projection, f32 zero stacks
        self._host: List[Dict[str, Dict[str, np.ndarray]]] = [
            {p: {"a": np.zeros((n, di, self.rank), np.float32),
                 "b": np.zeros((n, self.rank, do), np.float32)}
             for p, (di, do) in self.targets.items()}
            for _ in range(self.num_layers)]
        self._device = None            # lazy jit-input pytree cache
        self._slots: Dict[str, int] = {}          # name -> slot (>= 1)
        self._free: List[int] = list(range(n - 1, 0, -1))  # pop() -> 1..
        # live refs: adapter name -> request ids currently decoding with
        # it (id-keyed so re-acquire across preempt/migration/handoff is
        # idempotent; evict refuses while nonempty)
        self._refs: Dict[str, Set[str]] = {}
        self.loads = 0                 # lifetime load count
        self.evictions = 0

    # -- registry ----------------------------------------------------------

    def has(self, name: str) -> bool:
        return name in self._slots

    def adapters(self) -> Dict[str, int]:
        """{name: slot} for every resident adapter."""
        return dict(self._slots)

    @property
    def active_adapters(self) -> int:
        return len(self._slots)

    def slot_of(self, name: str) -> int:
        """Resolve an adapter name to its stack slot; typed
        :class:`UnknownAdapter` when it is not resident."""
        slot = self._slots.get(name)
        if slot is None:
            known = sorted(self._slots) or ["<none>"]
            raise UnknownAdapter(
                f"adapter {name!r} is not loaded in this pool "
                f"(resident: {', '.join(known)}) — LoRAPool.load it "
                "before admission")
        return slot

    def refcount(self, name: str) -> int:
        return len(self._refs.get(name, ()))

    # -- refcounts (Engine calls these; request-id keyed) ------------------

    def acquire(self, name: str, request_id: str) -> None:
        """Pin ``name`` for ``request_id`` (id-keyed set: idempotent).
        Typed :class:`UnknownAdapter` when the adapter is not resident —
        a blind ref on an evicted name would let its slot be zeroed or
        reused under the request."""
        self.slot_of(name)
        self._refs.setdefault(name, set()).add(request_id)

    def release(self, name: str, request_id: str) -> None:
        refs = self._refs.get(name)
        if refs is not None:
            refs.discard(request_id)

    # -- load / evict (value edits only — never a compile) -----------------

    def load(self, name: str, weights: Sequence[Dict[str, tuple]]) -> int:
        """Load (or hot-reload) adapter ``name``; returns its slot.

        ``weights`` is a per-layer sequence of ``{proj: (A, B)}`` dicts
        (``A (d_in, r)``, ``B (r, d_out)``; projections an adapter does
        not target may be omitted — their delta stays zero).  Reloading
        a resident name overwrites its slot in place (refcounts and the
        slot index survive, so live requests see the new weights on
        their next step — hot adapter UPDATE is the same buffer write
        as hot load)."""
        if len(weights) != self.num_layers:
            raise ValueError(
                f"adapter {name!r} carries {len(weights)} layers, pool "
                f"expects {self.num_layers}")
        scale = self.alpha / self.rank
        # validate + normalize EVERY row before touching pool state: a
        # mid-load failure must neither leak a popped slot nor leave a
        # resident adapter half-overwritten (live requests would decode
        # with mixed old/new layers on the next stack rebuild)
        rows = []
        for li, pack in enumerate(weights):
            unknown = set(pack or {}) - set(self.targets)
            if unknown:
                # a misnamed key (e.g. PEFT-style 'q_proj' for
                # 'self_attn.q_proj') silently loading as an all-zero
                # adapter would serve base outputs under the tenant's
                # name — reject loudly instead
                raise ValueError(
                    f"adapter {name!r} layer {li} targets unknown "
                    f"projection(s) {sorted(unknown)} — this pool "
                    f"targets {sorted(self.targets)}")
            for proj, (di, do) in self.targets.items():
                entry = (pack or {}).get(proj)
                if entry is None:
                    rows.append((li, proj, None, None))
                    continue
                a, b = entry
                a = np.asarray(a, np.float32)
                b = np.asarray(b, np.float32)
                if a.shape != (di, self.rank) or \
                        b.shape != (self.rank, do):
                    raise ValueError(
                        f"adapter {name!r} layer {li} {proj}: A{a.shape}"
                        f"/B{b.shape} do not match ({di}, {self.rank})/"
                        f"({self.rank}, {do})")
                # alpha/r folds here: the serving delta is then the
                # plain chain x @ A @ B and merge_adapter's reference
                # is W + A @ (B * alpha/r) — one scale definition
                rows.append((li, proj, a, b * scale))
        slot = self._slots.get(name)
        if slot is None:
            if not self._free:
                raise ValueError(
                    f"pool is full ({self.max_adapters} adapters) — "
                    f"evict one before loading {name!r}")
            slot = self._free.pop()
        for li, proj, a, b in rows:
            ha = self._host[li][proj]["a"]
            hb = self._host[li][proj]["b"]
            ha[slot] = 0.0 if a is None else a
            hb[slot] = 0.0 if b is None else b
        self._slots[name] = slot
        self._write_device_slot(slot)
        self.loads += 1
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.lora.loads").inc()
            reg.gauge("serve.lora.active_adapters").set(
                self.active_adapters)
        obs.emit_event("serve_lora_load", adapter=name, slot=slot,
                       rank=self.rank)
        return slot

    def evict(self, name: str) -> None:
        """Free ``name``'s slot (zeroing its rows).  Typed
        :class:`AdapterInUse` while live requests still reference it —
        never corrupt a decoding slot."""
        slot = self.slot_of(name)
        refs = self._refs.get(name)
        if refs:
            raise AdapterInUse(
                f"adapter {name!r} is referenced by {len(refs)} live "
                f"request(s) (e.g. {sorted(refs)[0]!r}) — drain before "
                "evicting")
        for li in range(self.num_layers):
            for proj in self.targets:
                self._host[li][proj]["a"][slot] = 0.0
                self._host[li][proj]["b"][slot] = 0.0
        del self._slots[name]
        self._refs.pop(name, None)
        self._free.append(slot)
        self._write_device_slot(slot)
        self.evictions += 1
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.lora.evictions").inc()
            reg.gauge("serve.lora.active_adapters").set(
                self.active_adapters)
        obs.emit_event("serve_lora_evict", adapter=name, slot=slot)

    # -- the jit-input pytree ----------------------------------------------

    def _write_device_slot(self, slot: int) -> None:
        """Scatter ONE slot's host rows into the cached device stacks —
        adapter churn then moves O(one slot) bytes instead of
        re-uploading the whole pool (num_slots× larger, on exactly the
        hot-load path the feature advertises as cheap).  The row index
        rides as a device scalar so every slot shares one compiled
        scatter per entry geometry; :meth:`prime_updates` compiles them
        at warmup, keeping churn inside the zero-compile contract."""
        if self._device is None:
            return                  # next device_stacks() builds fresh
        idx = jnp.asarray(slot, jnp.int32)
        for li in range(self.num_layers):
            for proj in self.targets:
                ent = self._device[li][proj]
                hp = self._host[li][proj]
                for k in ("a", "b"):
                    row = jnp.asarray(hp[k][slot], dtype=self.dtype)
                    ent[k] = ent[k].at[idx].set(row)

    def prime_updates(self) -> None:
        """Build the stacks and compile the per-slot scatter programs
        (a no-op rewrite of slot 0's zero rows) so the first real
        hot-load/evict after warmup hits the jit cache —
        ``Engine.warmup()`` calls this inside its compile window."""
        self.device_stacks()
        self._write_device_slot(0)

    def device_stacks(self):
        """Per-layer ``{proj: {"a": (N, d_in, r), "b": (N, r, d_out)}}``
        device arrays in the pool dtype — the fixed-shape jit input the
        engine threads through the compiled step.  Built once by full
        upload; adapter churn then edits slots in place
        (:meth:`_write_device_slot`) — fixed shapes throughout, so the
        step never retraces."""
        if self._device is None:
            self._device = [
                {proj: {k: jnp.asarray(arr, dtype=self.dtype)
                        for k, arr in ab.items()}
                 for proj, ab in pack.items()}
                for pack in self._host]
        return self._device

    def validate(self, model) -> None:
        """Geometry check at Engine construction: a pool built for one
        model family/shape must not silently serve another (the delta
        matmuls would retrace or misapply)."""
        layers = _decoder_layers(model)
        if len(layers) != self.num_layers or \
                _targets(layers[0]) != self.targets:
            raise ValueError(
                "LoRAPool geometry does not match this model "
                f"({self.num_layers} layers × {sorted(self.targets)} "
                "vs the engine's) — build the pool for the model the "
                "engine serves")

    def stats(self) -> Dict[str, float]:
        """Pool counters for telemetry/debugging."""
        return {"active_adapters": self.active_adapters,
                "max_adapters": self.max_adapters,
                "rank": self.rank, "loads": self.loads,
                "evictions": self.evictions,
                "live_refs": sum(len(v) for v in self._refs.values())}


def random_adapter(model, *, rank: int = 8, rng=None, scale: float = 0.05,
                   projs: Optional[Sequence[str]] = None):
    """Random adapter weights for tests/benches: per-layer
    ``{proj: (A, B)}`` with ``A ~ N(0, scale)`` and ``B ~ N(0, scale)``
    (non-zero B so the adapter visibly changes outputs — real LoRA
    training starts B at zero).  ``projs`` restricts the targeted
    projections (default: all)."""
    rng = rng if rng is not None else np.random.default_rng(0)
    layers = _decoder_layers(model)
    targets = _targets(layers[0])
    keys = list(targets) if projs is None else list(projs)
    out = []
    for _ in layers:
        pack = {}
        for p in keys:
            di, do = targets[p]
            pack[p] = (rng.normal(0.0, scale, (di, rank))
                       .astype(np.float32),
                       rng.normal(0.0, scale, (rank, do))
                       .astype(np.float32))
        out.append(pack)
    return out


def merge_adapter(model, weights, *, alpha: Optional[float] = None) -> int:
    """Fold adapter ``weights`` into ``model``'s projection weights IN
    PLACE: ``W += A @ B * (alpha/r)`` — the merged-weight REFERENCE the
    multi-LoRA identity tests compare the batched path against
    (token-identical greedy outputs; docs/SERVING.md "Multi-LoRA").
    Returns the number of projections merged."""
    layers = _decoder_layers(model)
    if len(weights) != len(layers):
        raise ValueError(
            f"adapter carries {len(weights)} layers, model has "
            f"{len(layers)}")
    merged = 0
    for layer, pack in zip(layers, weights):
        for proj, entry in (pack or {}).items():
            a, b = entry
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            r = a.shape[1]
            scale = (float(alpha) if alpha is not None else float(r)) / r
            sub, name = layer._resolve_path(proj + ".weight")
            w = sub._parameters[name]
            delta = (a @ (b * scale)).astype(np.float32)
            layer._assign_by_path(
                proj + ".weight",
                (w.astype(jnp.float32) + jnp.asarray(delta))
                .astype(w.dtype))
            merged += 1
    return merged
