"""Typed admission failures for the serving tier.

``Engine.add_request`` / ``FrontDoor.submit`` reject work for exactly
three reasons, and a production client must tell them apart without
string-matching a message: a *full queue* means "come back shortly", an
*unsatisfiable budget* means "this request can never fit — change it",
and a *rate limit* means "you, specifically, come back after
``retry_after_s``".  Bare ``ValueError``/``RuntimeError`` erased that
distinction, so every rejection is now a subclass of
:class:`AdmissionError`.

``AdmissionError`` deliberately subclasses ``ValueError``: every
pre-existing caller (and test) that caught ``ValueError`` on
``add_request`` keeps working — the hierarchy is additive.

The front door's load-shedding path does NOT raise by default: shed
requests get a typed :class:`~paddle_tpu.serving.frontdoor.Admission`
answer carrying the same reason + ``retry_after_s`` (an overloaded
server answering thousands of shed requests per second should not pay
exception unwinding per shed, and a shed is an expected outcome, not an
error).  ``FrontDoor.submit(raise_on_shed=True)`` opts into raising
these instead.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["AdapterInUse", "AdmissionError", "BudgetUnsatisfiable",
           "QueueFull", "RateLimited", "UnknownAdapter"]


class AdmissionError(ValueError):
    """Base: the serving tier refused to accept a request."""


class QueueFull(AdmissionError):
    """The bounded waiting queue is at capacity — retry later.

    ``retry_after_s`` (when known) is a load-based estimate of when a
    retry is likely to be admitted."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class BudgetUnsatisfiable(AdmissionError):
    """The request can NEVER be served by this engine geometry
    (prompt + max_new_tokens beyond ``max_seq_len``, or a KV-block
    budget larger than the whole pool).  Retrying cannot help — the
    request or the engine must change."""


class RateLimited(AdmissionError):
    """A tenant exceeded its token-bucket rate limit or quota.

    ``retry_after_s`` is the exact wait until the bucket can cover the
    request's token cost (or a load-based estimate for quota sheds)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class UnknownAdapter(AdmissionError):
    """The request names a LoRA adapter this engine has not loaded
    (``serving.LoRAPool`` — docs/SERVING.md "Multi-LoRA").  Raised at
    admission (``Engine.add_request`` / ``FrontDoor.submit``), never
    mid-decode: tenant→adapter mapping is validated before any state
    lands, so a bad mapping cannot strand a half-admitted request."""


class AdapterInUse(ValueError):
    """``LoRAPool.evict`` refused: live requests still reference the
    adapter's slot.  Evicting under readers would repoint their slot at
    zeros (or a later adapter's weights) mid-decode — the caller must
    drain or wait, not corrupt."""
