"""Durable cluster gateway: the HTTP front door over ``ClusterController``.

``ServingServer`` (server.py) fronts ONE engine replica in-process; the
gateway is the same OpenAI-ish surface over the *cluster* control plane
(serving/cluster.py) — the process a fleet actually exposes:

- ``POST /v1/completions`` admits through the controller's **durable
  admission journal** (``ClusterController.submit`` CAS-writes
  ``journal/<rid>`` before returning), so a request the gateway has
  answered with a rid survives a controller SIGKILL and is replayed by
  the standby's takeover.  An ``Idempotency-Key`` header (or body
  field) dedupes through the journal's ``jkey/<key>`` index: a
  duplicate POST returns the SAME rid and stream — never a second
  admission.
- **Tenancy/SLO shed in front of submit** reuses the front door's
  vocabulary (:class:`~paddle_tpu.serving.frontdoor.TenantPolicy`,
  :class:`~paddle_tpu.serving.frontdoor.TokenBucket`,
  :class:`~paddle_tpu.serving.frontdoor.Admission`): token-bucket rate
  limits, per-tenant live-request quotas, a gateway-wide live cap, and
  a backlog-driven SLO shed for tenants below the priority floor.
  Sheds map to HTTP exactly like server.py: 429 for
  ``rate_limited``/``quota`` (+ ``Retry-After``), 503 otherwise, and a
  draining gateway answers a typed 503 ``{"error": {"type":
  "draining"}}`` with a retry hint.
- **SSE streams off the fenced output record**: cluster workers publish
  one COMPLETE fenced record per request (``out/<rid>``, stale-epoch
  writes dropped), so the stream replays that record's tokens as SSE
  chunks the moment the controller collects it — the chunk shapes match
  server.py's, the delivery contract is the cluster's (exactly-once,
  epoch-fenced).
- **Graceful SIGTERM drain** via
  :class:`~paddle_tpu.launch.preempt.PreemptionGuard`: in-flight
  streams finish off the journal/outputs, new POSTs get the typed 503.

Fault site ``serve.gateway`` (docs/RESILIENCE.md) fires per admission
after the policy sheds and before the journal write: a fault sheds that
ONE request as a typed 503 — the gateway process and its in-flight
streams survive.

Threading model mirrors server.py: handler threads only *submit* (under
the gateway lock) and then wait on their request's delivery queue; ONE
loop thread drives ``ClusterController.pump()`` and routes collected
output records — the controller is never entered concurrently.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .. import observability as obs
from ..observability.sinks import registry_to_prometheus
from ..launch.preempt import PreemptionGuard
from ..resilience import _state as _rs_state
from .cluster import ClusterController, LeaseLost
from .frontdoor import Admission, TenantPolicy, TokenBucket

__all__ = ["ClusterGateway"]

_MAX_BODY = 8 << 20          # 8 MiB: a prompt, not an upload endpoint

#: the front door's shed vocabulary over HTTP (server.py's map; every
#: reason the gateway itself mints — draining, queue_full, slo_shed,
#: gateway_fault, journal, not_leader — lands on the 503 default)
_SHED_HTTP = {"rate_limited": 429, "quota": 429, "budget": 400}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle-tpu-gateway"

    def log_message(self, fmt, *args):  # noqa: D102 — stderr per request
        pass

    @property
    def gw(self) -> "ClusterGateway":
        return self.server.cluster_gateway  # type: ignore[attr-defined]

    def _json(self, code: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            self.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib name
        if self.path == "/healthz":
            self._json(200, self.gw.health())
        elif self.path == "/metrics":
            body = self.gw.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {"error": {"type": "not_found"}})

    def do_POST(self):  # noqa: N802 — stdlib name
        if self.path != "/v1/completions":
            self._json(404, {"error": {"type": "not_found"}})
            return
        gw = self.gw
        try:
            n = int(self.headers.get("Content-Length", 0))
            if not 0 < n <= _MAX_BODY:
                raise ValueError(f"bad Content-Length {n}")
            body = json.loads(self.rfile.read(n))
            prompt = [int(t) for t in body["prompt"]]
            max_tokens = int(body.get("max_tokens", 16))
            temperature = float(body.get("temperature", 0.0))
            stream = bool(body.get("stream", False))
            tenant = body.get("tenant") or body.get("user") \
                or self.headers.get("X-Tenant") or "default"
            key = self.headers.get("Idempotency-Key") \
                or body.get("idempotency_key")
        except Exception as e:  # noqa: BLE001 — malformed body
            # partly-read body on keep-alive would desync the next
            # request's parse: drop the connection with the error
            self.close_connection = True
            self._json(400, {"error": {"type": "invalid_request",
                                       "message": str(e)[:300]}})
            return

        q: "queue.Queue" = queue.Queue()
        adm = gw.submit_request(
            prompt, tenant=tenant, max_new_tokens=max_tokens,
            temperature=temperature, idempotency_key=key, deliver_to=q)
        if not adm.admitted:
            headers = {}
            if adm.retry_after_s is not None:
                headers["Retry-After"] = str(int(adm.retry_after_s + 0.5)
                                             or 1)
            self._json(_SHED_HTTP.get(adm.reason, 503),
                       {"error": {"type": adm.reason,
                                  "retry_after_s": adm.retry_after_s}},
                       headers=headers)
            return
        if stream:
            self._stream_response(adm.request_id, q, len(prompt))
        else:
            self._full_response(adm.request_id, q, len(prompt))

    def _wait(self, q):
        return q.get(timeout=self.gw.output_timeout_s)

    def _full_response(self, rid, q, prompt_len):
        try:
            rec = self._wait(q)
        except queue.Empty:
            self._json(504, {"error": {"type": "timeout", "id": rid}})
            return
        tokens = list(rec.get("tokens") or ())
        self._json(200, {
            "id": rid, "object": "text_completion",
            "choices": [{"index": 0, "token_ids": tokens,
                         "finish_reason": rec.get("reason")}],
            "usage": {"prompt_tokens": prompt_len,
                      "completion_tokens": len(tokens),
                      "total_tokens": prompt_len + len(tokens)}})

    def _stream_response(self, rid, q, prompt_len):
        """Replay the fenced output record as SSE chunks (server.py's
        chunk shapes): workers publish one COMPLETE record per request,
        so the stream opens when the controller collects it."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-store")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data: str):
            payload = f"data: {data}\n\n".encode()
            self.wfile.write(f"{len(payload):x}\r\n".encode()
                             + payload + b"\r\n")

        try:
            rec = self._wait(q)
            tokens = list(rec.get("tokens") or ())
            for i, tok in enumerate(tokens):
                fin = rec.get("reason") if i == len(tokens) - 1 else None
                chunk(json.dumps({
                    "id": rid, "object": "text_completion.chunk",
                    "choices": [{"index": 0, "token_id": int(tok),
                                 "finish_reason": fin}]}))
            chunk("[DONE]")
            self.wfile.write(b"0\r\n\r\n")
        except queue.Empty:
            chunk(json.dumps({"error": {"type": "timeout", "id": rid}}))
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass      # client went away; the cluster finishes anyway


class ClusterGateway:
    """The cluster's HTTP front door (module docstring for the full
    contract).  ``start()`` binds and returns ``(host, port)``;
    ``serve_forever()`` additionally installs a
    :class:`PreemptionGuard` and drains gracefully on SIGTERM (main
    thread only).  ``submit_request`` is the same admission path
    programmatically — the telemetry-overhead gate's poison probe and
    the unit tests drive it without a socket."""

    def __init__(self, controller: ClusterController,
                 host: str = "127.0.0.1", port: int = 0,
                 tenants: Optional[Dict[str, TenantPolicy]] = None,
                 max_live: int = 64,
                 slo_queue_depth: Optional[int] = None,
                 slo_priority_floor: int = 1,
                 poll_s: float = 0.005,
                 output_timeout_s: float = 120.0,
                 drain_retry_after_s: float = 1.0):
        self.ctl = controller
        self.tenants = dict(tenants) if tenants else \
            {"default": TenantPolicy()}
        self.max_live = int(max_live)
        self.slo_queue_depth = slo_queue_depth
        self.slo_priority_floor = int(slo_priority_floor)
        self.poll_s = float(poll_s)
        self.output_timeout_s = float(output_timeout_s)
        self.drain_retry_after_s = float(drain_retry_after_s)
        self._host, self._port = host, int(port)
        self._lock = threading.Lock()
        # rid → delivery queues (one per waiting handler thread;
        # duplicate Idempotency-Key streams share the rid) and
        # rid → tenant for quota accounting — written by handler
        # threads at submit, read/pruned by the pump loop; every touch
        # under _lock (pdtpu-lint lock-discipline)
        self._routes: Dict[str, List["queue.Queue"]] = {}  # guarded_by: _lock
        self._live_reqs: Dict[str, str] = {}               # guarded_by: _lock
        self._buckets: Dict[str, TokenBucket] = {}         # guarded_by: _lock
        self.shed_counts: Dict[str, int] = {}              # guarded_by: _lock
        self.n_admitted = 0                                # guarded_by: _lock
        self.dup_hits = 0                                  # guarded_by: _lock
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: list = []

    # -- admission ---------------------------------------------------------

    def _policy(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant) \
            or self.tenants.get("default") or TenantPolicy()

    # requires-lock: _lock
    def _bucket(self, tenant: str, pol: TenantPolicy) \
            -> Optional[TokenBucket]:
        if pol.rate_tokens_per_s is None:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            cap = pol.burst_tokens if pol.burst_tokens is not None \
                else pol.rate_tokens_per_s
            b = self._buckets[tenant] = TokenBucket(
                pol.rate_tokens_per_s, cap)
        return b

    # requires-lock: _lock
    def _shed(self, tenant: str, reason: str,
              retry_after_s: Optional[float]) -> Admission:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1
        reg = obs.get_registry()
        if reg is not None:
            reg.counter(f"gateway.shed[{reason}]").inc()
        obs.emit_event("serve_gateway", state="shed", tenant=tenant,
                       reason=reason, retry_after_s=retry_after_s)
        return Admission(False, None, reason, retry_after_s)

    def submit_request(self, prompt_ids, *, tenant: str = "default",
                       max_new_tokens: int = 16,
                       temperature: float = 0.0,
                       eos_token_id: Optional[int] = None,
                       idempotency_key: Optional[str] = None,
                       deliver_to: Optional["queue.Queue"] = None) \
            -> Admission:
        """Admit one request through shed policy → fault site → durable
        journal; returns the front door's typed :class:`Admission`.
        ``deliver_to`` (when given) receives the fenced output record
        once the controller collects it — the HTTP handlers' path."""
        prompt = [int(t) for t in prompt_ids]
        with self._lock:
            if self._draining.is_set():
                return self._shed(tenant, "draining",
                                  self.drain_retry_after_s)
            # a duplicate key is NOT a new admission: it bypasses the
            # policy sheds and replays the journaled rid's stream
            if idempotency_key is not None:
                dup = self.ctl._jkey_lookup(idempotency_key)
                if dup is not None:
                    self.dup_hits += 1
                    reg = obs.get_registry()
                    if reg is not None:
                        reg.counter("gateway.dup_hits").inc()
                    if deliver_to is not None:
                        self._routes.setdefault(dup, []).append(
                            deliver_to)
                    return Admission(True, dup, "duplicate", None)
            pol = self._policy(tenant)
            bucket = self._bucket(tenant, pol)
            if bucket is not None:
                wait = bucket.try_take(len(prompt) + int(max_new_tokens))
                if wait > 0:
                    return self._shed(tenant, "rate_limited",
                                      None if wait == float("inf")
                                      else wait)
            if pol.max_live_requests is not None:
                live = sum(1 for t in self._live_reqs.values() if t == tenant)
                if live >= pol.max_live_requests:
                    return self._shed(tenant, "quota",
                                      self.drain_retry_after_s)
            if len(self._live_reqs) >= self.max_live:
                return self._shed(tenant, "queue_full",
                                  self.drain_retry_after_s)
            if (self.slo_queue_depth is not None
                    and pol.priority < self.slo_priority_floor
                    and len(self.ctl._pending) + len(self._live_reqs)
                    >= self.slo_queue_depth):
                return self._shed(tenant, "slo_shed",
                                  self.drain_retry_after_s)
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                try:
                    fi("serve.gateway")
                except Exception:  # noqa: BLE001 — typed shed, not a 500
                    return self._shed(tenant, "gateway_fault",
                                      self.drain_retry_after_s)
            try:
                rid = self.ctl.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, eos_token_id=eos_token_id,
                    tenant=tenant, adapter=pol.adapter,
                    idempotency_key=idempotency_key)
            except LeaseLost:
                return self._shed(tenant, "not_leader",
                                  self.drain_retry_after_s)
            except Exception:  # noqa: BLE001 — journal retry exhausted
                return self._shed(tenant, "journal",
                                  self.drain_retry_after_s)
            self._live_reqs.setdefault(rid, tenant)
            if deliver_to is not None:
                self._routes.setdefault(rid, []).append(deliver_to)
            self.n_admitted += 1
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("gateway.admitted").inc()
            return Admission(True, rid, None, None)

    # -- delivery loop -----------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            delivered = []
            with self._lock:
                try:
                    if not self.ctl.follower:
                        self.ctl.pump()
                except LeaseLost:
                    pass      # fenced controller: streams time out typed
                except Exception:  # noqa: BLE001 — keep the loop alive
                    pass
                if self._routes:
                    outs = self.ctl.outputs
                    for rid in list(self._routes):
                        rec = outs.get(rid)
                        if rec is None:
                            continue
                        delivered.extend(
                            (q, rec) for q in self._routes.pop(rid))
                        self._live_reqs.pop(rid, None)
                if self._draining.is_set() and not self._live_reqs:
                    self._drained.set()
            for q, rec in delivered:
                q.put(rec)
            if not delivered:
                time.sleep(self.poll_s)

    # -- operational surface -----------------------------------------------

    def health(self) -> dict:
        """The ``GET /healthz`` body: gateway lifecycle + the
        controller's cheap local counters (no store scan per probe)."""
        with self._lock:
            return {
                "status": ("draining" if self._draining.is_set()
                           else "serving"),
                "follower": self.ctl.follower,
                "ctl_epoch": self.ctl.ctl_epoch,
                "live_requests": len(self._live_reqs),
                "pending": len(self.ctl._pending),
                "assigned": len(self.ctl._assigned),
                "admitted": self.n_admitted,
                "dup_hits": self.dup_hits,
                "shed": dict(self.shed_counts),
            }

    def metrics_text(self) -> str:
        """Prometheus text exposition: the fleet fold + gateway-local
        gauges (always scrape-able, telemetry on or off)."""
        with self._lock:
            extra = {
                "gateway.live_requests": len(self._live_reqs),
                "gateway.draining": 1 if self._draining.is_set() else 0,
                "gateway.admitted": self.n_admitted,
                "gateway.dup_hits": self.dup_hits,
                "cluster.pending_refs": len(self.ctl._pending),
                "cluster.collected_outputs": len(self.ctl._outs),
            }
            for reason, n in self.shed_counts.items():
                extra[f"gateway.shed[{reason}]"] = n
        return registry_to_prometheus(self.ctl.fleet_registry(),
                                      extra=extra)

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self):
        return (self._host, self._port)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def start(self):
        """Bind, start the HTTP listener + pump loop threads; returns
        ``(host, port)`` (the OS-assigned port when built with 0)."""
        if self._httpd is not None:
            return self.address

        class _Srv(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = _Srv((self._host, self._port), _Handler)
        self._httpd.cluster_gateway = self     # type: ignore[attr-defined]
        self._host, self._port = self._httpd.server_address[:2]
        for target, name in ((self._httpd.serve_forever, "http"),
                             (self._loop, "pump-loop")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"cluster-gateway-{name}")
            t.start()
            self._threads.append(t)
        obs.emit_event("serve_gateway", state="started",
                       host=self._host, port=self._port)
        return self.address

    def begin_drain(self, reason: str = "requested") -> None:
        """Stop admitting (typed 503 + Retry-After); in-flight streams
        finish off the fenced output records."""
        if not self._draining.is_set():
            self._draining.set()
            obs.emit_event("serve_gateway", state="draining",
                           reason=reason)

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def close(self) -> None:
        """Tear down listener + loop threads (does NOT wait for drain —
        ``begin_drain()``/``wait_drained()`` first for graceful)."""
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        obs.emit_event("serve_gateway", state="closed")

    def serve_forever(self) -> None:
        """Block until SIGTERM, then drain gracefully and return.  Main
        thread only (installs a signal handler via PreemptionGuard)."""
        self.start()
        guard = PreemptionGuard()
        try:
            with guard:
                while not self._stop.is_set() and not guard.preempted:
                    time.sleep(max(self.poll_s, 0.01))
        finally:
            self.begin_drain(reason="sigterm" if guard.preempted
                             else "closed")
            self.wait_drained(timeout=self.output_timeout_s)
            self.close()
