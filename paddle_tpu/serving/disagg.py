"""Disaggregated serving: prefill/decode role specialization with
KV-page streaming over a cluster tier (docs/SERVING.md "Disaggregated
serving").

The colocated stack runs both phases in every replica, so a burst of
long prompts stalls every decode slot behind prefill compute.  This
module splits the fleet: ``role="prefill"`` engines retire each request
at prefill-complete (first token sampled and emitted — TTFT stops on
the prefill tier — pages swapped to host, slot freed) and
``role="decode"`` engines resume the request from a transferred
:class:`KVHandout` through the existing restore path, so TTFT and
aggregate tok/s scale on INDEPENDENT axes.  The transfer primitive is
the one PR 6 built: ``SwapManager``'s fixed-shape compiled
gather/scatter already turns "move a request between hosts" into "ship
its KV pages as host-RAM bytes" — this module only frames, verifies,
and routes those bytes.

Three layers:

- :class:`KVHandout` — the wire unit: one request's identity (prompt,
  budget, sampling seed, trace id) plus its resume state (``kv_len``,
  pending first token, emitted ids) plus the swapped page payload
  (``SwapManager.payload_to_bytes`` framing — int8 scale rows
  included), round-tripping through bytes so any engine with the same
  pool geometry restores byte-identical KV.
- :class:`KVTransport` — chunked puts with per-chunk AND whole-payload
  crc32 verification on receive, ``RetryPolicy``-wrapped I/O over the
  ``serve.xfer.put`` / ``serve.xfer.get`` fault sites.  Two
  implementations: :class:`LoopbackTransport` (in-process dict — tests
  and single-host sets) and :class:`StoreTransport` (TCPStore-keyed —
  the multi-host tier, using the store client's per-call ``timeout=``
  override so multi-megabyte page chunks get a longer deadline than
  heartbeats).
- :class:`DisaggReplicaSet` — duck-types the ``EngineReplicaSet``
  surface behind the unchanged FrontDoor: admissions route to the
  least-loaded prefill replica (prefix affinity probes the prefill
  tier's caches), handoffs stream to the decode replica with the most
  free blocks, trace ids and exact phase accounting survive the hop
  (the transfer is the ``xfer`` trace segment between ``prefill`` and
  the decode-side ``queue`` wait).  A hard transfer failure degrades
  that request to a fresh re-prefill on the decode replica — greedy
  outputs regenerate token-identical, exactly like the DP evacuation
  fallback.  Replica failure is role-aware: a dead decode replica's
  in-flight requests re-enter the handoff queue; a dead prefill
  replica's queued admissions re-route to the surviving prefill tier
  (or, when a whole tier is gone, the other tier runs colocated).
  :class:`HeartbeatMonitor` wires the TCPStore heartbeat machinery in:
  stale or unparsable beats fail the replica through the same
  evacuation path.

Zero-recompile contract: every path here is host bookkeeping plus the
already-compiled step/CoW/swap programs — the ``serving-disagg`` CI
gate churns the set under injected ``serve.xfer.*`` faults and a
decode-replica kill and demands token-identity with a colocated run
and zero compiles after warmup.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import struct
import time
import warnings
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..observability import _state as _obs_state
from ..resilience import _state as _rs_state
from ..resilience.retry import DEFAULT_RETRYABLE, RetryPolicy
from .block_allocator import SwapManager
from .distributed import EngineReplicaSet
from .scheduler import Request, RequestState

__all__ = ["DisaggReplicaSet", "HeartbeatMonitor", "KVHandout",
           "KVTransport", "LoopbackTransport", "StoreTransport",
           "TransferError"]


class TransferError(RuntimeError):
    """A KV-page transfer chunk is missing or failed its crc32 check.
    Retryable under the transport's policy (a torn concurrent put may
    resolve); exhausting the retries is a HARD transfer failure and the
    replica set degrades the request to a fresh re-prefill."""


# ---------------------------------------------------------------------------
# the wire unit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class KVHandout:
    """One request, packaged to move between replicas: identity +
    resume state + the swapped KV page payload.

    ``payload`` is a ``SwapManager.swap_out`` host payload (per-layer
    tuples of ``(pages, page, H_kv, D)`` numpy rows; int8 pools carry
    the two scale arrays per layer too).  ``to_bytes``/``from_bytes``
    round-trip the whole handout through one bytes blob — the format
    :class:`KVTransport` ships and the ``serving-disagg`` gate's
    token-identity leans on.  Host-local fields that cannot ride a wire
    (the ``on_token`` streaming callback) re-attach at
    ``Engine.admit_handout``."""

    request_id: str
    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float
    eos_token_id: Optional[int]
    tenant: Optional[str]
    trace_id: Optional[str]
    kv_len: int
    pending_token: Optional[int]
    output_ids: List[int]
    sample_seed: int
    preempts: int
    handoffs: int
    submit_t: float
    first_token_t: Optional[float]
    pages: int
    payload: Optional[list]
    # multi-LoRA: the adapter NAME rides the wire (slot indices are
    # engine-local — the receiving engine re-resolves against its own
    # pool at admit_handout, rejecting typed if the adapter is absent)
    adapter: Optional[str] = None

    @classmethod
    def from_state(cls, st: RequestState) -> "KVHandout":
        """Package a handed-off (swapped) request state."""
        if st.swapped is None:
            raise ValueError(
                f"request {st.request.request_id!r} has no swapped "
                "payload — only a prefill-complete (or preempted) state "
                "can be handed out")
        pages, host = st.swapped
        req = st.request
        return cls(
            request_id=req.request_id,
            prompt_ids=np.asarray(req.prompt_ids, np.int32),
            max_new_tokens=int(req.max_new_tokens),
            temperature=float(req.temperature),
            eos_token_id=req.eos_token_id,
            tenant=req.tenant, trace_id=req.trace_id,
            kv_len=int(st.kv_len), pending_token=st.pending_token,
            output_ids=list(st.output_ids),
            sample_seed=int(st.sample_seed), preempts=int(st.preempts),
            handoffs=int(st.handoffs), submit_t=float(st.submit_t),
            first_token_t=st.first_token_t,
            pages=int(pages), payload=host, adapter=req.adapter)

    def to_state(self, on_token=None) -> RequestState:
        """Reconstruct the request state on the receiving engine; the
        restore path scatters ``payload`` into freshly allocated blocks
        and decode resumes at ``kv_len`` (scheduler.admit_next)."""
        req = Request(prompt_ids=self.prompt_ids,
                      max_new_tokens=self.max_new_tokens,
                      temperature=self.temperature,
                      eos_token_id=self.eos_token_id, on_token=on_token,
                      request_id=self.request_id, tenant=self.tenant,
                      adapter=self.adapter)
        req.trace_id = self.trace_id
        st = RequestState(req)
        st.kv_len = int(self.kv_len)
        st.pending_token = self.pending_token
        st.output_ids = list(self.output_ids)
        st.sample_seed = int(self.sample_seed)
        st.preempts = int(self.preempts)
        st.handoffs = int(self.handoffs)
        st.submit_t = float(self.submit_t)
        st.first_token_t = self.first_token_t
        # restored pages come back all-private (same rule as preemption)
        st.swapped = (int(self.pages), self.payload) if self.pages \
            else None
        return st

    def to_bytes(self) -> bytes:
        """One blob: length-prefixed JSON meta, then the prompt's raw
        int32 bytes, then the ``SwapManager.payload_to_bytes`` frame."""
        prompt = np.ascontiguousarray(
            np.asarray(self.prompt_ids, np.int32))
        blob = SwapManager.payload_to_bytes(self.payload) if self.pages \
            else b""
        meta = {"v": 1, "request_id": self.request_id,
                "max_new_tokens": self.max_new_tokens,
                "temperature": self.temperature,
                "eos_token_id": self.eos_token_id,
                "tenant": self.tenant, "trace_id": self.trace_id,
                "adapter": self.adapter,
                "kv_len": self.kv_len,
                "pending_token": self.pending_token,
                "output_ids": list(self.output_ids),
                "sample_seed": self.sample_seed,
                "preempts": self.preempts, "handoffs": self.handoffs,
                "submit_t": self.submit_t,
                "first_token_t": self.first_token_t,
                "pages": self.pages,
                "prompt_len": int(prompt.size),
                "payload_nbytes": len(blob)}
        hdr = json.dumps(meta).encode()
        return b"".join([struct.pack("<I", len(hdr)), hdr,
                         prompt.tobytes(), blob])

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVHandout":
        (hlen,) = struct.unpack_from("<I", data, 0)
        meta = json.loads(data[4:4 + hlen].decode())
        if meta.get("v") != 1:
            raise ValueError(
                f"unknown KVHandout format version {meta.get('v')!r}")
        off = 4 + hlen
        plen = int(meta["prompt_len"])
        prompt = np.frombuffer(data, dtype=np.int32, count=plen,
                               offset=off)
        off += plen * 4
        blob = data[off:]
        if len(blob) != int(meta["payload_nbytes"]):
            raise TransferError(
                f"handout framing mismatch: meta promises "
                f"{meta['payload_nbytes']} payload bytes, blob carries "
                f"{len(blob)}")
        payload = SwapManager.payload_from_bytes(blob) if meta["pages"] \
            else None
        return cls(
            request_id=meta["request_id"], prompt_ids=prompt,
            max_new_tokens=int(meta["max_new_tokens"]),
            temperature=float(meta["temperature"]),
            eos_token_id=meta["eos_token_id"], tenant=meta["tenant"],
            trace_id=meta["trace_id"], kv_len=int(meta["kv_len"]),
            pending_token=meta["pending_token"],
            output_ids=[int(t) for t in meta["output_ids"]],
            sample_seed=int(meta["sample_seed"]),
            preempts=int(meta["preempts"]),
            handoffs=int(meta["handoffs"]),
            submit_t=float(meta["submit_t"]),
            first_token_t=meta["first_token_t"],
            pages=int(meta["pages"]), payload=payload,
            adapter=meta.get("adapter"))


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class KVTransport:
    """Chunked, crc-verified, retried KV-page transfer.

    Subclasses provide the byte store (``_put_chunk``/``_get_chunk``/
    ``_put_meta``/``_get_meta``/``_delete``); this base owns the
    framing every implementation shares — ``chunk_bytes``-sized pieces,
    each framed as ``crc32 + length + bytes`` and verified on receive
    (a corrupt chunk raises :class:`TransferError` and re-fetches under
    the retry policy), plus a whole-payload crc in the meta record so a
    reassembly bug can never hand the engine silently wrong pages.  The
    meta record lands LAST on put, so a concurrent getter never
    observes a half-written transfer.  Every chunk I/O runs through the
    ``serve.xfer.put`` / ``serve.xfer.get`` fault sites inside the
    ``RetryPolicy`` (default: 3 attempts, crc failures retryable), so
    an injected or transient fault is a logged retry and exhaustion is
    the hard failure the replica set degrades on."""

    def __init__(self, *, chunk_bytes: int = 1 << 20,
                 retry: Optional[RetryPolicy] = None):
        if chunk_bytes < 16:
            raise ValueError(
                f"chunk_bytes must be >= 16, got {chunk_bytes}")
        self.chunk_bytes = int(chunk_bytes)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, backoff_s=0.01,
            retryable=DEFAULT_RETRYABLE + (TransferError,))
        self.puts = 0            # lifetime completed transfers out
        self.gets = 0            # lifetime completed transfers in
        self.bytes_out = 0
        self.bytes_in = 0
        self.crc_errors = 0      # chunks that failed verification

    # -- the byte store (subclass responsibility) --------------------------

    def _put_chunk(self, key: str, i: int, framed: bytes) -> None:
        raise NotImplementedError

    def _get_chunk(self, key: str, i: int) -> Optional[bytes]:
        raise NotImplementedError

    def _put_meta(self, key: str, meta: bytes) -> None:
        raise NotImplementedError

    def _get_meta(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def _delete(self, key: str, chunks: int) -> None:
        raise NotImplementedError

    # -- framing -----------------------------------------------------------

    def put(self, key: str, data: bytes) -> int:
        """Stream ``data`` under ``key`` in verified chunks; returns the
        chunk count.  Meta lands last."""
        n = max(1, -(-len(data) // self.chunk_bytes))
        for i in range(n):
            blob = data[i * self.chunk_bytes:(i + 1) * self.chunk_bytes]
            framed = struct.pack("<II", zlib.crc32(blob), len(blob)) + blob

            def attempt(i=i, framed=framed):
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    fi("serve.xfer.put")
                self._put_chunk(key, i, framed)

            self.retry.run(attempt, site="serve.xfer.put")
        meta = json.dumps({"chunks": n, "nbytes": len(data),
                           "crc32": zlib.crc32(data)}).encode()

        def put_meta():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("serve.xfer.put")
            self._put_meta(key, meta)

        self.retry.run(put_meta, site="serve.xfer.put")
        self.puts += 1
        self.bytes_out += len(data)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.xfer.puts").inc()
            reg.counter("serve.xfer.bytes_out").inc(len(data))
        return n

    def get(self, key: str, *, delete: bool = True) -> bytes:
        """Reassemble ``key``'s payload, verifying every chunk's crc32
        and the whole-payload crc; ``delete`` reclaims the store entry
        once the bytes are safely out."""
        def get_meta():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("serve.xfer.get")
            m = self._get_meta(key)
            if m is None:
                raise TransferError(f"transfer {key!r}: no meta record")
            return m

        meta = json.loads(self.retry.run(get_meta,
                                         site="serve.xfer.get").decode())
        parts = []
        for i in range(int(meta["chunks"])):

            def attempt(i=i):
                fi = _rs_state.FAULTS[0]
                if fi is not None:
                    fi("serve.xfer.get")
                framed = self._get_chunk(key, i)
                if framed is None:
                    raise TransferError(
                        f"transfer {key!r}: chunk {i} missing")
                crc, ln = struct.unpack_from("<II", framed, 0)
                blob = framed[8:]
                if len(blob) != ln or zlib.crc32(blob) != crc:
                    self.crc_errors += 1
                    reg = obs.get_registry()
                    if reg is not None:
                        reg.counter("serve.xfer.crc_errors").inc()
                    raise TransferError(
                        f"transfer {key!r}: chunk {i} failed crc32 "
                        "verification")
                return blob

            parts.append(self.retry.run(attempt, site="serve.xfer.get"))
        data = b"".join(parts)
        if len(data) != int(meta["nbytes"]) \
                or zlib.crc32(data) != int(meta["crc32"]):
            raise TransferError(
                f"transfer {key!r}: reassembled payload failed the "
                "whole-blob crc32 check")
        if delete:
            self._delete(key, int(meta["chunks"]))
        self.gets += 1
        self.bytes_in += len(data)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.xfer.gets").inc()
            reg.counter("serve.xfer.bytes_in").inc(len(data))
        return data

    def discard(self, key: str, nbytes: int) -> None:
        """Best-effort cleanup of an ABANDONED transfer (a hard put/get
        failure): delete the meta record and every chunk an
        ``nbytes``-sized payload could have written.  Without this, a
        half-put transfer's multi-megabyte chunks would pin the store's
        RAM forever — keys are unique per attempt, so nothing ever
        overwrites them."""
        chunks = max(1, -(-int(nbytes) // self.chunk_bytes))
        try:
            self._delete(key, chunks)
        except Exception:  # noqa: BLE001 — cleanup must never mask the
            pass           # failure that got us here

    def stats(self) -> Dict[str, int]:
        return {"puts": self.puts, "gets": self.gets,
                "bytes_out": self.bytes_out, "bytes_in": self.bytes_in,
                "crc_errors": self.crc_errors}


class LoopbackTransport(KVTransport):
    """In-process transport: the byte store is a dict.  Tests and
    single-host disaggregated sets — the full framing (chunking, crc,
    retries, fault sites) still runs, so loopback exercises the same
    wire format the store transport ships."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._blobs: Dict[tuple, bytes] = {}

    def _put_chunk(self, key, i, framed):
        self._blobs[(key, "c", i)] = framed

    def _get_chunk(self, key, i):
        return self._blobs.get((key, "c", i))

    def _put_meta(self, key, meta):
        self._blobs[(key, "m")] = meta

    def _get_meta(self, key):
        return self._blobs.get((key, "m"))

    def _delete(self, key, chunks):
        self._blobs.pop((key, "m"), None)
        for i in range(chunks):
            self._blobs.pop((key, "c", i), None)

    def __len__(self):
        return len(self._blobs)


class StoreTransport(KVTransport):
    """TCPStore-keyed transport: the multi-host tier.  Chunks land
    under ``<prefix>/<key>/c<i>`` and the meta record under
    ``<prefix>/<key>/meta`` on the rendezvous store every host already
    reaches.  Page chunks are megabytes where heartbeats are bytes, so
    every store op uses the client's per-call ``timeout=`` override
    (``op_timeout_s``) instead of stretching the store's default
    deadline for everyone."""

    def __init__(self, store, *, prefix: str = "serve/xfer",
                 op_timeout_s: float = 30.0, **kw):
        super().__init__(**kw)
        self.store = store
        self.prefix = prefix.rstrip("/")
        self.op_timeout_s = float(op_timeout_s)

    def _k(self, key: str, part: str) -> str:
        return f"{self.prefix}/{key}/{part}"

    def _put_chunk(self, key, i, framed):
        self.store.set(self._k(key, f"c{i}"), framed,
                       timeout=self.op_timeout_s)

    def _get_chunk(self, key, i):
        return self.store.get(self._k(key, f"c{i}"),
                              timeout=self.op_timeout_s)

    def _put_meta(self, key, meta):
        self.store.set(self._k(key, "meta"), meta,
                       timeout=self.op_timeout_s)

    def _get_meta(self, key):
        return self.store.get(self._k(key, "meta"),
                              timeout=self.op_timeout_s)

    def _delete(self, key, chunks):
        self.store.delete(self._k(key, "meta"))
        for i in range(chunks):
            self.store.delete(self._k(key, f"c{i}"))


# ---------------------------------------------------------------------------
# heartbeats (the TCPStore liveness half of cross-role health)
# ---------------------------------------------------------------------------

class HeartbeatMonitor:
    """TCPStore-keyed replica liveness: each replica's host loop writes
    ``<prefix>/<i>`` with a monotonic timestamp (:meth:`beat`);
    :meth:`stale` names the replicas whose beat is older than
    ``deadline_s`` — or unparsable, which counts as dead (the
    ElasticManager rule: garbage from a dying process is not a
    heartbeat).  ``DisaggReplicaSet.attach_heartbeats`` reaps stale
    replicas through the same ``_fail_replica`` evacuation path an
    in-step exception takes, so a host that stops beating loses its
    requests to the survivors, not to the void."""

    def __init__(self, store, n_replicas: int, *,
                 prefix: str = "serve/hb", deadline_s: float = 10.0,
                 interval_s: Optional[float] = None,
                 clock=time.monotonic):
        self.store = store
        self.n = int(n_replicas)
        self.prefix = prefix.rstrip("/")
        self.deadline_s = float(deadline_s)
        # how often the set runs a beat+reap round: stepping is
        # per-token cadence and a round costs 2N store RPCs, so probing
        # every step would turn liveness into hot-path I/O — a third of
        # the deadline keeps detection latency identical at a fraction
        # of the traffic (tests pass 0.0 for every-step rounds)
        self.interval_s = float(deadline_s) / 3.0 if interval_s is None \
            else float(interval_s)
        self.clock = clock

    def beat(self, i: int) -> None:
        self.store.set(f"{self.prefix}/{i}",
                       f"{self.clock():.6f}".encode())

    def stale(self) -> List[int]:
        """Replica indices whose beat is missing-after-first-beat is NOT
        stale (a replica that never registered is simply not monitored
        yet); present-but-old or unparsable IS."""
        out = []
        now = self.clock()
        for i in range(self.n):
            raw = self.store.get(f"{self.prefix}/{i}")
            if raw is None:
                continue
            try:
                ts = float(raw.decode())
            except (ValueError, UnicodeDecodeError):
                out.append(i)        # unparsable == dead
                continue
            if now - ts > self.deadline_s:
                out.append(i)
        return out


# ---------------------------------------------------------------------------
# the disaggregated replica set
# ---------------------------------------------------------------------------

class DisaggReplicaSet(EngineReplicaSet):
    """Prefill tier + decode tier behind one Engine-shaped surface.

    ``prefill`` engines must be ``role="prefill"``, ``decode`` engines
    ``role="decode"``; all share geometry (the base class check — a
    handout must restore into any decode replica's pools).  The
    FrontDoor drives this exactly like an ``EngineReplicaSet``: its
    tenancy/shed/SLO policy is unchanged, only placement differs —

    - **admission** routes to the least-loaded healthy PREFILL replica
      (prefix affinity probes the prefill tier's caches, so a repeated
      system prompt pins to the replica already holding its pages);
    - **handoff**: after each step, every prefill-complete state
      streams through ``transport`` (put → get → crc verify) to the
      healthy decode replica with the most free blocks, arriving via
      ``Engine.admit_handout`` — the ``xfer`` trace segment between
      prefill and the decode-side queue wait.  A HARD transfer failure
      (retries exhausted) degrades that request to a fresh re-prefill
      on the decode replica: greedy outputs regenerate identically,
      the same trade as DP evacuation's reset path;
    - **replica failure** is role-aware: a dead decode replica's
      in-flight requests re-enter the handoff queue (their page
      payloads already live in host RAM); a dead prefill replica's
      queued admissions re-route to the surviving prefill tier.  When
      a whole tier is gone the other tier runs colocated — a
      prefill-role engine with no decode capacity keeps decoding
      locally (the ``_handoff_ok`` veto), and with no prefill tier
      fresh prompts land on decode replicas, whose unified step
      prefills them just fine.
    """

    def __init__(self, prefill: Sequence, decode: Sequence, *,
                 transport: Optional[KVTransport] = None,
                 prefix_affinity: bool = True):
        prefill, decode = list(prefill), list(decode)
        if not prefill or not decode:
            raise ValueError(
                "DisaggReplicaSet needs at least one prefill and one "
                "decode replica")
        for tier, want in ((prefill, "prefill"), (decode, "decode")):
            for e in tier:
                if getattr(e, "role", "both") != want:
                    raise ValueError(
                        f"every {want}-tier engine must be built with "
                        f"role={want!r}, got role={getattr(e, 'role', None)!r} "
                        "(Engine(role=...))")
        super().__init__(prefill + decode, prefix_affinity=prefix_affinity)
        self.n_prefill = len(prefill)
        self._prefill_idx = tuple(range(len(prefill)))
        self._decode_idx = tuple(range(len(prefill),
                                       len(prefill) + len(decode)))
        self.transport = transport if transport is not None \
            else LoopbackTransport()
        # states popped off a prefill engine (or a dead decode replica)
        # awaiting transfer: drained to empty inside every step(), so
        # run()'s has_work never races a parked request
        self._handoff_queue: "collections.deque" = \
            collections.deque()                  # guarded_by: _lock
        self._xfer_seq = itertools.count()
        self.xfers = 0               # lifetime completed transfers
        self.xfer_failures = 0       # hard failures (degraded to reset)
        self.xfer_bytes = 0
        self._hb: Optional[HeartbeatMonitor] = None
        self._hb_next = 0.0          # next beat+reap round (monitor clock)
        self._hb_last: Optional[float] = None   # our last beat round
        for e in prefill:
            # veto hook: with no healthy decode replica, prefill
            # engines keep decoding locally instead of parking requests
            # nobody will ever pick up
            e._handoff_ok = self._decode_capacity

    # -- introspection -----------------------------------------------------

    @property
    def roles(self) -> List[str]:
        return [r.role for r in self.replicas]

    def disagg_stats(self) -> Dict[str, float]:
        """Handoff/transfer counters + the transport's own."""
        out = {"handoffs": sum(self.replicas[i].handoffs
                               for i in self._prefill_idx),
               "xfers": self.xfers,
               "xfer_failures": self.xfer_failures,
               "xfer_bytes": self.xfer_bytes}
        for k, v in self.transport.stats().items():
            out[f"transport_{k}"] = v
        return out

    # requires-lock: _lock — reads the health map
    def _decode_capacity(self) -> bool:
        return any(self._health[i] for i in self._decode_idx)

    # -- routing (admission goes to the prefill tier) ----------------------

    # requires-lock: _lock
    def _route_candidates(self) -> List[int]:
        cands = [i for i in self._prefill_idx if self._health[i]]
        if cands:
            return cands
        # the whole prefill tier is down: decode replicas' unified step
        # can prefill too — degraded colocated mode beats an outage
        return [i for i in self._decode_idx if self._health[i]]

    # requires-lock: _lock
    def _pick_decode(self) -> Optional[int]:
        """The handoff target: healthy decode replica with the most
        free blocks (pages land there), ties broken by the router's
        load key."""
        cands = [i for i in self._decode_idx if self._health[i]]
        if not cands:
            return None
        return min(cands, key=lambda i: (
            -self.replicas[i].kv.allocator.free_blocks,
            *self._load_key(i)))

    # -- stepping + handoff draining ---------------------------------------

    # requires-lock: _lock — the loop-thread entry point
    def step(self) -> List:
        events = super().step()
        self._drain_handoffs()
        if self._hb is not None:
            self._beat_and_reap()
        return events

    # requires-lock: _lock
    def has_work(self) -> bool:
        return bool(self._handoff_queue) or any(
            r.has_work() or bool(r.handed_off)
            for i, r in enumerate(self.replicas) if self._health[i])

    # requires-lock: _lock — drains handed_off/_handoff_queue
    def _drain_handoffs(self) -> None:
        """Transfers run SYNCHRONOUSLY inside step(), like the
        preemption swap I/O they are built from: a slow store op holds
        the step for its retry budget, so size ``op_timeout_s`` and the
        transport retry policy for the data plane, not the default
        store deadline (a future multi-process tier moves this off the
        step loop entirely — each decode host pulls from the store)."""
        for i in self._prefill_idx:
            r = self.replicas[i]
            while r.handed_off:
                st = r.handed_off.popleft()
                r._states.pop(st.request.request_id, None)
                self._handoff_queue.append((i, st))
        while self._handoff_queue:
            src, st = self._handoff_queue.popleft()
            self._transfer(src, st)

    # requires-lock: _lock — places into _states/_placements
    def _adopt(self, tgt: int, st, rid: str) -> None:
        eng = self.replicas[tgt]
        if eng.lora is not None and st.request.adapter is not None:
            # the prefill engine released its reference at handoff
            # commit; adoption bypasses admit_handout, so re-resolve
            # the slot and re-acquire BEFORE any state lands (same
            # order as admit_handout): a typed UnknownAdapter from an
            # evict that raced the zero-ref handoff window must not
            # leave a half-adopted request on tgt's scheduler
            st.request.adapter_slot = eng.lora.slot_of(
                st.request.adapter)
            eng.lora.acquire(st.request.adapter, rid)
        eng._states[rid] = st
        eng.scheduler.requeue(st)
        self._placements[rid] = tgt

    # requires-lock: _lock
    def _transfer(self, src: int, st) -> None:
        """Stream ONE handed-off state to the decode tier: serialize →
        chunked put → get + crc verify → ``admit_handout`` on the
        target.  The round-trip through bytes runs even on loopback —
        the wire format IS the contract, so the in-process set proves
        exactly what a multi-host set ships."""
        rid = st.request.request_id
        tr = _obs_state.TRACE[0]
        tgt = self._pick_decode()
        if tgt is None:
            # no decode tier left: adopt on any healthy replica and let
            # it decode locally (its restore path consumes st.swapped)
            cands = [i for i in range(len(self.replicas))
                     if self._health[i]]
            if not cands:
                raise RuntimeError(
                    "no healthy replicas left to place a handoff")
            tgt = min(cands, key=self._load_key)
            self._adopt(tgt, st, rid)
            if tr is not None:
                tr.transition(rid, "queue", event="xfer",
                              from_replica=src, to_replica=tgt,
                              degraded="no_decode_replica")
            return
        t0 = time.perf_counter()
        key = f"{rid}/{next(self._xfer_seq)}"
        on_token = st.request.on_token
        data = None
        try:
            handout = KVHandout.from_state(st)
            data = handout.to_bytes()
            self.transport.put(key, data)
            raw = self.transport.get(key)
            self.replicas[tgt].admit_handout(raw, on_token=on_token)
        except Exception as e:  # noqa: BLE001 — hard transfer failure
            if data is not None:
                # reclaim whatever the dead transfer left in the store
                # (half-put chunks, or a full payload whose get failed)
                self.transport.discard(key, len(data))
            self.xfer_failures += 1
            warnings.warn(
                f"KV transfer for request {rid!r} failed hard "
                f"({type(e).__name__}: {e}); degrading to a fresh "
                f"re-prefill on replica {tgt}", RuntimeWarning,
                stacklevel=3)
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("serve.xfer.failures").inc()
            obs.emit_event("serve_xfer_fail", id=rid,
                           from_replica=src, to_replica=tgt,
                           exc=type(e).__name__, message=str(e)[:200])
            # the PR-8 evacuation fallback: KV is unrecoverable over
            # this transport — re-prefill from scratch on the target
            # (greedy regenerates identical tokens; a streaming
            # consumer sees the regenerated prefix twice, same caveat
            # as the DP hard-reset path)
            self._reset_to_fresh(st)
            self._adopt(tgt, st, rid)
            if tr is not None:
                tr.transition(rid, "queue", event="reset_fresh",
                              from_replica=src, to_replica=tgt)
            return
        ms = (time.perf_counter() - t0) * 1e3
        self.xfers += 1
        self.xfer_bytes += len(data)
        self._placements[rid] = tgt
        if tr is not None:
            # closes the xfer segment opened at first token on the
            # prefill replica; the decode-side queue wait starts here
            tr.transition(rid, "queue", event="xfer", from_replica=src,
                          to_replica=tgt, bytes=len(data),
                          pages=handout.pages)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.xfer.transfers").inc()
        obs.emit_event("serve_xfer", id=rid, from_replica=src,
                       to_replica=tgt, bytes=len(data),
                       pages=handout.pages, ms=round(ms, 3))

    # -- role-aware failure handling ---------------------------------------

    # requires-lock: _lock
    def _fail_replica(self, idx: int, exc: Exception) -> None:
        rep = self.replicas[idx]
        # parked handoffs survive their replica: the page payloads are
        # already host-RAM bytes, so they just re-enter the queue
        while rep.handed_off:
            st = rep.handed_off.popleft()
            rep._states.pop(st.request.request_id, None)
            self._handoff_queue.append((idx, st))
        super()._fail_replica(idx, exc)
        # place everything the evacuation queued (including the dead
        # decode replica's preempted in-flight requests) right away
        self._drain_handoffs()

    # requires-lock: _lock
    def _evacuate_waiting(self, idx: int, st, exc, tr) -> None:
        rid = st.request.request_id
        if st.swapped is not None and not st.prefilling:
            # decode-ready state off a dead decode replica: its pages
            # are host bytes — re-enter the handoff queue and stream to
            # a surviving decode replica
            self._handoff_queue.append((idx, st))
            return
        # fresh / reset / mid-prefill state: back to the prefill tier
        cands = [i for i in self._prefill_idx if self._health[i]] or \
            [i for i in range(len(self.replicas)) if self._health[i]]
        if not cands:
            raise RuntimeError(
                "no healthy replicas left to evacuate onto") from exc
        tgt = min(cands, key=self._load_key)
        self._adopt(tgt, st, rid)
        if tr is not None:
            tr.point(rid, "migrate", from_replica=idx, to_replica=tgt)

    # -- heartbeats --------------------------------------------------------

    def attach_heartbeats(self, monitor: HeartbeatMonitor
                          ) -> "DisaggReplicaSet":
        """Wire TCPStore liveness into the step loop: every step first
        reaps replicas whose beat went stale (through the same
        role-aware evacuation as an in-step failure), then beats for
        the replicas this process drives."""
        if monitor.n != len(self.replicas):
            raise ValueError(
                f"monitor covers {monitor.n} replicas, the set has "
                f"{len(self.replicas)}")
        self._hb = monitor
        return self

    # requires-lock: _lock
    def _beat_and_reap(self) -> None:
        hb = self._hb
        now = hb.clock()
        if now < self._hb_next:
            return                   # rate-limited: see interval_s
        self._hb_next = now + hb.interval_s
        # self-stall guard: when THIS driver also writes the beats (the
        # in-process set), a step-loop pause longer than the deadline
        # would make every beat look stale at once and the reap below
        # would destroy the whole healthy set over a transient GC/host
        # hiccup.  If WE have not beaten within the deadline, the
        # staleness is ours — re-beat and let the next round measure.
        stalled = self._hb_last is not None \
            and now - self._hb_last > hb.deadline_s
        if not stalled:
            for i in hb.stale():
                if self._health[i]:
                    self._fail_replica(i, TimeoutError(
                        f"replica {i} heartbeat stale (>"
                        f"{hb.deadline_s}s or unparsable)"))
        for i in range(len(self.replicas)):
            if self._health[i]:
                hb.beat(i)
        self._hb_last = now
