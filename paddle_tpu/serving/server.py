"""Thin streaming HTTP server over the serving front door.

Pure stdlib (``http.server``/``socketserver``) — the process a fleet
actually runs in front of one engine replica: an OpenAI-ish completions
endpoint per tenant, server-sent-event streaming straight off
``Engine.stream()``'s token events, typed shed answers as HTTP status +
``Retry-After``, and graceful drain on SIGTERM via
:class:`~paddle_tpu.launch.preempt.PreemptionGuard` — in-flight
requests finish, new ones get a 503 with a retry hint, and the process
exits with every KV block reclaimed.

Protocol (``POST /v1/completions``, JSON body)::

    {"prompt": [1, 2, 3] | "text...",   # token ids, or text if the
                                        # server was built with tokenize=
     "max_tokens": 16, "temperature": 0.0, "stream": false,
     "tenant": "default"}               # or the X-Tenant header

Responses: 200 with ``choices[0].token_ids`` (+ ``text`` when the
engine detokenizes); ``"stream": true`` switches to ``text/event-stream``
chunks ending in ``data: [DONE]``.  Sheds map to HTTP: 429 for
``rate_limited``/``quota`` (with ``Retry-After``), 503 for
``queue_full``/``slo_shed``/draining, 400 for ``budget`` and malformed
bodies.  ``GET /healthz`` reports serving/degraded/draining and live
depths — over a replica set (DP or disaggregated) it carries one row
per replica with its role, health, queue depth, and free blocks, and
the top-level status flips to ``degraded`` the moment any replica is
dead (before this, a degraded set answered healthy with no way to see
which replica died).

Operational surface (docs/OBSERVABILITY.md "Tracing a request"):
``GET /metrics`` serves the live registry as Prometheus text exposition
(``observability.sinks.registry_to_prometheus``; engine-local gauges
when telemetry is off, so the endpoint is always scrape-able), and
``GET /v1/requests/<rid>`` returns that request's lifecycle timeline
from the request tracer (404 unknown, 503 when tracing is off).  An
``X-Trace-Id`` request header on ``POST /v1/completions`` propagates
the caller's trace id into the request's timeline
(``observability.trace_context``).

Threading model: handler threads only ever *submit* (under the server
lock) and then read their request's event queue; ONE loop thread drives
``FrontDoor.step()`` and routes events — the engine itself is never
entered concurrently.
"""

from __future__ import annotations

import contextlib
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .. import observability as obs
from ..observability.sinks import registry_to_prometheus
from ..observability.trace import trace_context
from ..launch.preempt import PreemptionGuard
from .engine import Engine
from .frontdoor import FrontDoor

__all__ = ["ServingServer"]

_MAX_BODY = 8 << 20          # 8 MiB: a prompt, not an upload endpoint


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle-tpu-serving"

    # the BaseHTTPRequestHandler default logs every request to stderr
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def srv(self) -> "ServingServer":
        return self.server.serving_server  # type: ignore[attr-defined]

    def _json(self, code: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            # tell the client (not just the socket): http.client then
            # reconnects transparently on its next request
            self.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _replica_health(eng):
        """Per-replica role/health rows when ``eng`` is a replica set
        (``EngineReplicaSet`` / ``DisaggReplicaSet``), else None.  A
        degraded set must SAY so: before this, a set with a dead
        replica answered ``healthy`` with no way to see which replica
        died or what role the fleet lost."""
        replicas = getattr(eng, "replicas", None)
        if replicas is None:
            return None, True
        health = list(getattr(eng, "_health", [True] * len(replicas)))
        rows = [{"index": i,
                 "role": getattr(r, "role", "both"),
                 "healthy": bool(health[i]),
                 "queue_depth": r.scheduler.queue_depth(),
                 "active": len(r.scheduler.active()),
                 "free_blocks": r.kv.allocator.free_blocks}
                for i, r in enumerate(replicas)]
        return rows, all(health)

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            srv = self.srv
            with srv._lock:
                eng = srv.door.engine
                replicas, all_healthy = self._replica_health(eng)
                status = "draining" if srv.draining else \
                    ("serving" if all_healthy else "degraded")
                payload = {
                    "status": status,
                    "queue_depth": srv.door.queue_depth(),
                    "active_requests": len(eng.scheduler.active()),
                    "kv_blocks_used": eng.kv_blocks_used,
                }
                if replicas is not None:
                    payload["replicas"] = replicas
                else:
                    payload["role"] = getattr(eng, "role", "both")
            self._json(200, payload)
        elif self.path == "/metrics":
            self._metrics()
        elif self.path.startswith("/v1/requests/"):
            from urllib.parse import unquote
            # strip any query string: /v1/requests/req-7?pretty=1 must
            # look up "req-7", not "req-7?pretty=1"
            rid = self.path[len("/v1/requests/"):].split("?", 1)[0]
            self._request_timeline(unquote(rid))
        else:
            self._json(404, {"error": {"type": "not_found"}})

    def _metrics(self):
        """Prometheus text exposition of the live registry; with
        telemetry disabled, the engine-local gauges still render so the
        endpoint is always scrape-able (never a 500 or an empty 200)."""
        srv = self.srv
        with srv._lock:
            eng = srv.door.engine
            live = {
                "serve.queue_depth": srv.door.queue_depth(),
                "serve.active_requests": len(eng.scheduler.active()),
                "serve.kv_blocks_used": eng.kv_blocks_used,
                "serve.draining": 1 if srv.draining else 0,
            }
            replicas, all_healthy = self._replica_health(eng)
            if replicas is not None:
                # per-replica liveness is scrape-able even with the
                # telemetry registry off: serve_replica_healthy{replica=i}
                live["serve.degraded"] = 0 if all_healthy else 1
                for row in replicas:
                    i = row["index"]
                    live[f"serve.replica[{i}].healthy"] = \
                        1 if row["healthy"] else 0
                    live[f"serve.replica[{i}].is_prefill"] = \
                        1 if row["role"] == "prefill" else 0
        reg = obs.get_registry()
        body = registry_to_prometheus(reg, extra=live).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _request_timeline(self, rid: str):
        """One request's lifecycle timeline (docs/OBSERVABILITY.md):
        the request tracer's ordered events + exact phase summary."""
        tr = obs.get_request_tracer()
        if tr is None:
            self._json(503, {"error": {
                "type": "tracing_disabled",
                "message": "enable observability with request_tracing "
                           "to serve request timelines"}})
            return
        tl = tr.timeline(rid)
        if tl is None:
            self._json(404, {"error": {"type": "not_found", "id": rid}})
            return
        self._json(200, tl)

    def do_POST(self):  # noqa: N802
        if self.path != "/v1/completions":
            self._json(404, {"error": {"type": "not_found"}})
            return
        srv = self.srv
        try:
            n = int(self.headers.get("Content-Length", 0))
            if not 0 < n <= _MAX_BODY:
                raise ValueError(f"bad Content-Length {n}")
            body = json.loads(self.rfile.read(n))
            prompt = body["prompt"]
            if isinstance(prompt, str):
                if srv.tokenize is None:
                    raise ValueError(
                        "text prompts need a server built with "
                        "tokenize=; send token ids instead")
                prompt = srv.tokenize(prompt)
            prompt = [int(t) for t in prompt]
            max_tokens = int(body.get("max_tokens", 16))
            temperature = float(body.get("temperature", 0.0))
            stream = bool(body.get("stream", False))
            tenant = body.get("tenant") or body.get("user") \
                or self.headers.get("X-Tenant") or "default"
        except Exception as e:  # noqa: BLE001 — malformed body
            # the body may be partly (or not at all) read: answering on
            # a keep-alive stream would desync the next request's parse,
            # so drop the connection with the error
            self.close_connection = True
            self._json(400, {"error": {"type": "invalid_request",
                                       "message": str(e)[:300]}})
            return

        if srv.draining:
            # the typed drain answer: come back once a healthy replica
            # picks up (the front door's shed vocabulary over HTTP)
            ra = srv.drain_retry_after_s
            self._json(503, {"error": {"type": "draining",
                                       "retry_after_s": ra}},
                       headers={"Retry-After": str(int(ra + 0.5) or 1)})
            return

        q: "queue.Queue" = queue.Queue()
        # a caller-supplied trace id joins the request's lifecycle
        # timeline (GET /v1/requests/<rid>); contextvars keep concurrent
        # handler threads' ids from bleeding into each other
        trace_id = self.headers.get("X-Trace-Id")
        ctx = trace_context(trace_id) if trace_id \
            else contextlib.nullcontext()
        with srv._lock, ctx:
            adm = srv.door.submit(prompt, tenant=tenant,
                                  max_new_tokens=max_tokens,
                                  temperature=temperature)
            if adm.admitted:
                srv._routes[adm.request_id] = q
        if not adm.admitted:
            code = {"rate_limited": 429, "quota": 429,
                    "budget": 400}.get(adm.reason, 503)
            headers = {}
            if adm.retry_after_s is not None:
                headers["Retry-After"] = str(int(adm.retry_after_s + 0.5)
                                             or 1)
            self._json(code, {"error": {
                "type": adm.reason, "retry_after_s": adm.retry_after_s}},
                headers=headers)
            return

        rid = adm.request_id
        if stream:
            self._stream_response(rid, q, len(prompt))
        else:
            self._full_response(rid, q, len(prompt))

    def _next_event(self, q):
        ev = q.get(timeout=self.srv.token_timeout_s)
        return ev

    def _full_response(self, rid, q, prompt_len):
        tokens, texts, reason = [], [], None
        try:
            while True:
                ev = self._next_event(q)
                tokens.append(ev.token_id)
                if ev.text is not None:
                    texts.append(ev.text)
                if ev.finished:
                    reason = ev.finish_reason
                    break
        except queue.Empty:
            self._json(504, {"error": {"type": "timeout", "id": rid}})
            return
        self._json(200, {
            "id": rid, "object": "text_completion",
            "choices": [{"index": 0,
                         "text": "".join(texts) if texts else None,
                         "token_ids": tokens, "finish_reason": reason}],
            "usage": {"prompt_tokens": prompt_len,
                      "completion_tokens": len(tokens),
                      "total_tokens": prompt_len + len(tokens)}})

    def _stream_response(self, rid, q, prompt_len):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-store")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data: str):
            payload = f"data: {data}\n\n".encode()
            self.wfile.write(f"{len(payload):x}\r\n".encode()
                             + payload + b"\r\n")

        try:
            while True:
                ev = self._next_event(q)
                chunk(json.dumps({
                    "id": rid, "object": "text_completion.chunk",
                    "choices": [{"index": 0, "token_id": ev.token_id,
                                 "text": ev.text,
                                 "finish_reason": ev.finish_reason}]}))
                if ev.finished:
                    break
            chunk("[DONE]")
            self.wfile.write(b"0\r\n\r\n")
        except queue.Empty:
            chunk(json.dumps({"error": {"type": "timeout", "id": rid}}))
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass          # client went away; the engine finishes anyway


class ServingServer:
    """One engine replica behind an HTTP front door.

    ``door`` is a :class:`FrontDoor` (a bare warmed :class:`Engine` is
    wrapped in a default one).  ``start()`` spins the listener and the
    engine loop thread and returns ``(host, port)``;
    ``serve_forever()`` additionally installs a
    :class:`PreemptionGuard` and blocks until SIGTERM, then drains
    gracefully (must run on the MAIN thread — signal handlers cannot be
    installed elsewhere).  ``begin_drain()``/``wait_drained()``/
    ``close()`` expose the same lifecycle programmatically."""

    def __init__(self, door, host: str = "127.0.0.1", port: int = 0,
                 tokenize: Optional[Callable] = None,
                 poll_s: float = 0.002, token_timeout_s: float = 120.0,
                 drain_retry_after_s: float = 1.0):
        if isinstance(door, Engine):
            door = FrontDoor(door)
        self.door: FrontDoor = door
        self.tokenize = tokenize
        self.poll_s = float(poll_s)
        self.token_timeout_s = float(token_timeout_s)
        self.drain_retry_after_s = float(drain_retry_after_s)
        self._host, self._port = host, int(port)
        self._lock = threading.Lock()
        # request_id → handler-thread event queue; written by handler
        # threads at submit, read/pruned by the engine-loop thread —
        # every touch under _lock (pdtpu-lint lock-discipline)
        self._routes: dict = {}                      # guarded_by: _lock
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: list = []

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self):
        return (self._host, self._port)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def start(self):
        """Bind, start the HTTP listener + engine loop threads; returns
        ``(host, port)`` (the OS-assigned port when built with 0)."""
        if self._httpd is not None:
            return self.address

        class _Srv(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = _Srv((self._host, self._port), _Handler)
        self._httpd.serving_server = self      # type: ignore[attr-defined]
        self._host, self._port = self._httpd.server_address[:2]
        for target, name in ((self._httpd.serve_forever, "http"),
                             (self._loop, "engine-loop")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"serving-server-{name}")
            t.start()
            self._threads.append(t)
        obs.emit_event("serve_server", state="started", host=self._host,
                       port=self._port)
        return self.address

    def _loop(self):
        while not self._stop.is_set():
            evs = ()
            with self._lock:
                if self.door.has_work():
                    evs = self.door.step()
            for ev in evs:
                # under the lock: handler threads insert routes
                # concurrently (lint's lock-discipline rule flagged the
                # bare read here — a handler registering its queue
                # between this get and the pop could be missed)
                with self._lock:
                    q = self._routes.get(ev.request_id)
                    if q is not None and ev.finished:
                        self._routes.pop(ev.request_id, None)
                if q is not None:
                    q.put(ev)
            if self._draining.is_set():
                with self._lock:
                    idle = not self.door.has_work()
                if idle:
                    self._drained.set()
            if not evs:
                time.sleep(self.poll_s)

    def begin_drain(self, reason: str = "requested") -> None:
        """Stop accepting new requests (503 + Retry-After); in-flight
        requests keep streaming until the engine empties."""
        if not self._draining.is_set():
            self._draining.set()
            obs.emit_event("serve_server", state="draining",
                           reason=reason)

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def close(self) -> None:
        """Tear down listener + loop threads (does NOT wait for drain —
        call ``begin_drain()``/``wait_drained()`` first for graceful)."""
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        obs.emit_event("serve_server", state="closed")

    def serve_forever(self) -> None:
        """Block until SIGTERM, then drain gracefully and return.  Main
        thread only (installs a signal handler via PreemptionGuard)."""
        self.start()
        guard = PreemptionGuard()
        try:
            with guard:
                while not self._stop.is_set() and not guard.preempted:
                    time.sleep(max(self.poll_s, 0.01))
        finally:
            self.begin_drain(reason="sigterm" if guard.preempted
                             else "closed")
            self.wait_drained(timeout=self.token_timeout_s)
            self.close()
