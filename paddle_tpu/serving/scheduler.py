"""Continuous-batching scheduler: admission queue + fixed-shape slots.

The whole point of this module is that the compiled decode step NEVER
retraces: the decode batch is always ``max_batch`` slots with static
array shapes — ``tokens (B,)``, ``block_tables (B, MB)``,
``context_lens (B,)``, ``temps (B,)`` — and requests join/leave a
running batch purely by editing the VALUES in those arrays:

- an **active** slot carries its real block-table row, KV length and
  pending token;
- an **inactive** slot carries the out-of-range block sentinel
  (scatters drop), length 0 and token 0 — its lane computes garbage the
  engine discards, which on TPU is cheaper than a recompile by ~5
  orders of magnitude (see the recompile sentinel's storm warning).

Admission reserves every block a request can ever need
(``ceil((prompt + max_new) / page)``) up front, so decode can never die
on pool exhaustion — a full pool only delays the waiting queue.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Request", "RequestState", "Scheduler"]

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One user request: prompt + decode policy."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 16
    temperature: float = 0.0        # 0 = greedy, >0 = sampling
    eos_token_id: Optional[int] = None
    on_token: Optional[Callable] = None   # cb(request_id, token_id, text)
    request_id: Optional[str] = None

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.request_id is None:
            self.request_id = f"req-{next(_ids)}"


class RequestState:
    """A request occupying a slot (or still waiting)."""

    __slots__ = ("request", "slot", "blocks", "table", "kv_len",
                 "pending_token", "output_ids", "text_len", "detok_offset",
                 "submit_t", "first_token_t", "finished", "finish_reason",
                 "drained")

    def __init__(self, request: Request):
        self.request = request
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.table: Optional[np.ndarray] = None   # (MB,) int32
        self.kv_len = 0              # tokens whose KV sits in the pool
        self.pending_token: Optional[int] = None  # emitted, KV not written
        self.output_ids: List[int] = []
        self.text_len = 0            # chars already streamed from the
        self.detok_offset = 0        # ...detok window starting here
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.finished = False
        self.finish_reason: Optional[str] = None
        self.drained = False         # returned by an Engine.run() already

    @property
    def total_len(self) -> int:
        return int(self.request.prompt_ids.size) + self.request.max_new_tokens


class Scheduler:
    """Waiting queue + the fixed slot bucket."""

    def __init__(self, max_batch: int, page_size: int,
                 max_blocks_per_seq: int, allocator, oob_block: int):
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.allocator = allocator
        self.oob_block = int(oob_block)
        self.waiting: "collections.deque[RequestState]" = collections.deque()
        self.slots: List[Optional[RequestState]] = [None] * self.max_batch

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request) -> RequestState:
        st = RequestState(request)
        self.waiting.append(st)
        return st

    def queue_depth(self) -> int:
        return len(self.waiting)

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def blocks_for(self, total_len: int) -> int:
        """Blocks a ``total_len``-token sequence reserves: ceil(len/page).
        The ONE place this formula lives — Engine.add_request's
        unsatisfiable-budget rejection must agree with admission."""
        return -(-int(total_len) // self.page_size)

    def blocks_needed(self, st: RequestState) -> int:
        return self.blocks_for(st.total_len)

    def admit_next(self) -> Optional[RequestState]:
        """Move the head of the waiting queue into a slot, reserving its
        full block budget.  FIFO head-of-line: a large head request
        waits for blocks rather than being starved by later small ones.
        Returns the admitted state, or None (no slot / no blocks / no
        waiters)."""
        if not self.waiting:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        st = self.waiting[0]
        need = self.blocks_needed(st)
        if not self.allocator.can_allocate(need):
            return None
        self.waiting.popleft()
        st.slot = slot
        st.blocks = self.allocator.allocate(need)
        st.table = np.full((self.max_blocks_per_seq,), self.oob_block,
                           np.int32)
        st.table[:need] = st.blocks
        self.slots[slot] = st
        return st

    # -- the running batch -------------------------------------------------

    def active(self) -> List[Tuple[int, RequestState]]:
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    def batch_arrays(self):
        """The fixed-shape decode inputs: (tokens, tables, lens, temps)
        as numpy arrays.  Inactive slots get the inert sentinel values —
        shapes NEVER depend on occupancy."""
        b, mb = self.max_batch, self.max_blocks_per_seq
        tokens = np.zeros((b,), np.int32)
        tables = np.full((b, mb), self.oob_block, np.int32)
        lens = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        for i, st in self.active():
            tokens[i] = st.pending_token
            tables[i] = st.table
            lens[i] = st.kv_len
            temps[i] = st.request.temperature
        return tokens, tables, lens, temps

    def finish(self, st: RequestState, reason: str) -> None:
        """Release the slot and reclaim every reserved block."""
        st.finished = True
        st.finish_reason = reason
        if st.slot is not None:
            self.slots[st.slot] = None
            st.slot = None
        if st.blocks:
            self.allocator.free(st.blocks)
            st.blocks = []

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)
