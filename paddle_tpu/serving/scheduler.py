"""Continuous-batching scheduler: admission queue + fixed-shape ragged
slots.

The whole point of this module is that the compiled serving step NEVER
retraces: the batch is always ``max_batch`` slots with static array
shapes — ``tokens (B, C)``, ``block_tables (B, MB)``,
``span_starts (B,)``, ``span_lens (B,)``, ``temps (B,)`` — and requests
join/leave a running batch purely by editing the VALUES in those arrays:

- a slot mid-PREFILL carries its next ≤C-token prompt chunk starting at
  ``kv_len`` (chunked prefill — no per-length bucket programs, no
  head-of-line stall while a long prompt prefills);
- a DECODING slot carries its single pending token (span length 1);
- an idle/inactive slot carries span length 0 and the out-of-range
  block sentinel (scatters drop) — its lane computes garbage the engine
  discards, which on TPU is cheaper than a recompile by ~5 orders of
  magnitude (see the recompile sentinel's storm warning).

Admission reserves every block a request can ever WRITE up front
(``ceil((prompt + max_new) / page)`` minus read-only prefix-cache hits),
so decode can never die on pool exhaustion — a full pool only delays the
waiting queue.  Prefix-cache hits map shared blocks into the new table
and reserve only the remainder; a hit covering the WHOLE prompt keeps
the last matched page borrowed, re-prefills its final token, and
reserves a private replacement for the copy-on-write the engine performs
before that write (serving/block_allocator.py has the lifecycle).

Per-step chunk budgeting: ``plan_spans(chunk, budget)`` caps the TOTAL
prefill tokens scheduled per step and round-robins the budget across
prefilling slots, so on TPU (where the ragged kernel skips dead pages) a
burst of admissions cannot stretch one step's latency unboundedly —
decode slots always advance.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .block_allocator import PrefixCache

__all__ = ["Request", "RequestState", "Scheduler"]

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One user request: prompt + decode policy."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 16
    temperature: float = 0.0        # 0 = greedy, >0 = sampling
    eos_token_id: Optional[int] = None
    on_token: Optional[Callable] = None   # cb(request_id, token_id, text)
    request_id: Optional[str] = None
    tenant: Optional[str] = None    # front-door attribution (telemetry)
    # request-lifecycle trace id (observability/trace.py): filled by the
    # tracer at submit when tracing is on; riding the Request keeps the
    # id with the state through preempt/restore and replica migration
    trace_id: Optional[str] = None
    # multi-LoRA (docs/SERVING.md "Multi-LoRA"): the adapter NAME is the
    # request's portable identity (it rides preempt/restore, replica
    # migration and the disagg wire format); adapter_slot is the
    # engine-local stack index the admitting engine resolves via its
    # LoRAPool — 0 (the exact no-op) for base-model requests
    adapter: Optional[str] = None
    adapter_slot: int = 0

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.request_id is None:
            self.request_id = f"req-{next(_ids)}"


class RequestState:
    """A request occupying a slot (or still waiting)."""

    __slots__ = ("request", "slot", "blocks", "table", "kv_len",
                 "pending_token", "output_ids", "text_len", "detok_offset",
                 "submit_t", "first_token_t", "finished", "finish_reason",
                 "drained", "num_shared", "num_cowed", "cached_tokens",
                 "borrowed", "cow_spare", "page_keys", "swapped",
                 "preempts", "handoffs", "sample_seed", "draft",
                 "spec_proposed", "spec_accepted")

    def __init__(self, request: Request):
        self.request = request
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.table: Optional[np.ndarray] = None   # (MB,) int32
        self.kv_len = 0              # tokens whose KV sits in the pool
        self.pending_token: Optional[int] = None  # emitted, KV not written
        self.output_ids: List[int] = []
        self.text_len = 0            # chars already streamed from the
        self.detok_offset = 0        # ...detok window starting here
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.finished = False
        self.finish_reason: Optional[str] = None
        self.drained = False         # returned by an Engine.run() already
        self.num_shared = 0          # prefix-cache pages borrowed
        self.num_cowed = 0           # of those, privatized by CoW since
        self.cached_tokens = 0       # prompt tokens skipped via the cache
        self.borrowed: Set[int] = set()   # shared pages we may yet write
        self.cow_spare: Dict[int, int] = {}   # page → reserved CoW block
        self.page_keys: List[bytes] = []      # full-prompt-page digests
        # preemption: (pages, host payload) while swapped to host RAM —
        # admission takes the restore path instead of a fresh prefill
        self.swapped: Optional[tuple] = None
        self.preempts = 0            # times this request was preempted
        self.handoffs = 0            # prefill→decode replica transfers
        #                              (disaggregated serving, disagg.py)
        # per-request sampling stream seed (finalized in
        # Scheduler.submit, which folds in its per-engine submission
        # ordinal): the temperature stream depends only on (engine key,
        # prompt, submission index, emit index) — reproducible across
        # identical engines and the speculative/non-speculative split,
        # while DUPLICATE prompts in one engine still sample distinct
        # streams (best-of-n must not collapse to n copies).  Stored on
        # the state, so it survives preempt→restore, replica migration,
        # and hard re-prefill resets (engine._sample).
        self.sample_seed = zlib.crc32(
            request.prompt_ids.tobytes()) & 0x7FFFFFFF
        # speculative decoding (serving/spec.py): this step's draft
        # tokens (transient — set by the engine before planning, never
        # part of any snapshot) and lifetime acceptance accounting
        self.draft: List[int] = []
        self.spec_proposed = 0       # draft tokens sent to verification
        self.spec_accepted = 0       # of those, accepted

    @property
    def total_len(self) -> int:
        return int(self.request.prompt_ids.size) + self.request.max_new_tokens

    @property
    def prefilling(self) -> bool:
        return self.kv_len < int(self.request.prompt_ids.size)


class Scheduler:
    """Waiting queue + the fixed slot bucket."""

    def __init__(self, max_batch: int, page_size: int,
                 max_blocks_per_seq: int, allocator, oob_block: int,
                 prefix_cache: Optional[PrefixCache] = None):
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.allocator = allocator
        self.oob_block = int(oob_block)
        self.prefix_cache = prefix_cache
        # Cross-thread when driven through a ServingServer: handler
        # threads observe the queue via FrontDoor while the loop
        # thread admits from it — serialized by ServingServer._lock
        # (pdtpu-lint lock-discipline; single-threaded drivers
        # trivially hold it).
        self.waiting: "collections.deque[RequestState]" = \
            collections.deque()                  # guarded_by: _lock
        self.slots: List[Optional[RequestState]] = [None] * self.max_batch
        self._rr = 0   # round-robin origin for the prefill token budget
        self._submits = 0   # submission ordinal folded into sample seeds

    # -- admission ---------------------------------------------------------

    # requires-lock: _lock
    def submit(self, request: Request,
               page_keys: Optional[List[bytes]] = None) -> RequestState:
        st = RequestState(request)
        # fold the submission ordinal into the sampling seed: identical
        # prompts submitted twice must draw DISTINCT temperature
        # streams (best-of-n), while the same engine driven the same
        # way stays reproducible (RequestState.sample_seed)
        st.sample_seed = (st.sample_seed ^ (self._submits * 0x9E3779B1)
                          ) & 0x7FFFFFFF
        self._submits += 1
        if self.prefix_cache is not None:
            # hash the prompt's pages ONCE here: admit_next runs every
            # step, and a request parked at the queue head under
            # pool-exhaustion backpressure must not re-run O(prompt)
            # blake2b chains per retry.  A caller that already hashed
            # them (the replica router's affinity probe) passes them in.
            # The adapter name salts the chain: adapter deltas change
            # the KV content, so prefix sharing is PER ADAPTER.
            st.page_keys = page_keys if page_keys is not None else \
                PrefixCache.page_keys(
                    request.prompt_ids, self.page_size,
                    salt=request.adapter.encode()
                    if request.adapter else b"")
        self.waiting.append(st)
        return st

    # requires-lock: _lock
    def queue_depth(self) -> int:
        return len(self.waiting)

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def blocks_for(self, total_len: int) -> int:
        """Blocks a ``total_len``-token sequence reserves: ceil(len/page).
        The ONE place this formula lives — Engine.add_request's
        unsatisfiable-budget rejection must agree with admission."""
        return -(-int(total_len) // self.page_size)

    def blocks_needed(self, st: RequestState) -> int:
        return self.blocks_for(st.total_len)

    # requires-lock: _lock
    def admit_next(self) -> Optional[RequestState]:
        """Move the head of the waiting queue into a slot.  FIFO
        head-of-line: a large head request waits for blocks rather than
        being starved by later small ones.  With a prefix cache, hit
        pages are borrowed (refcount shared) and only the remainder is
        reserved; prefill resumes at the cached length.  Returns the
        admitted state, or None (no slot / no blocks / no waiters)."""
        if not self.waiting:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        st = self.waiting[0]
        if st.swapped is not None:
            # RESTORE path: a preempted request re-enters with its KV
            # bytes parked on host.  Every page is re-materialized as a
            # PRIVATE block (no prefix borrowing: the cached entry that
            # backed a borrowed page may have been evicted since, and
            # the host payload is the authoritative content) — the
            # engine swap_ins pages [0, ceil(kv_len/page)) right after
            # this returns, then prefill/decode resumes at kv_len.
            total = self.blocks_needed(st)
            if not self.allocator.can_allocate(total):
                return None
            self.waiting.popleft()
            st.slot = slot
            st.blocks = self.allocator.allocate(total)
            st.table = np.full((self.max_blocks_per_seq,), self.oob_block,
                               np.int32)
            st.table[:total] = st.blocks
            self.slots[slot] = st
            return st
        plen = int(st.request.prompt_ids.size)
        total = self.blocks_needed(st)
        keys = st.page_keys                    # hashed once at submit()
        hit_blocks: List[int] = []
        if self.prefix_cache is not None:
            hit_blocks = self.prefix_cache.lookup(keys)
        shared = len(hit_blocks)
        # physical capacity: reviving a refcount-0 cached hit consumes a
        # unit of free capacity too (can_allocate counts evictable blocks
        # as free, but share() takes them out of that pool), and a fully
        # cached prompt's CoW spare needs one block beyond
        # blocks_for(total) — so the full hit may not fit even when the
        # no-hit path would.  Degrade the hit page by page until it
        # fits; shared == 0 is the plain path, eventually satisfiable
        # because add_request guarantees total <= num_blocks.
        while True:
            # always leave >= 1 prompt token to prefill: the first
            # output token needs the last prompt position's logits, and
            # a fully cached prompt would otherwise skip the forward
            first_write = min(shared * self.page_size, plen - 1)
            ro_pages = first_write // self.page_size   # never written
            need_private = total - ro_pages
            revive = sum(1 for bid in hit_blocks[:shared]
                         if self.allocator.refcount(bid) == 0)
            if self.allocator.can_allocate(need_private + revive):
                break
            if shared == 0:
                return None
            shared -= 1
        hit_blocks = hit_blocks[:shared]
        for bid in hit_blocks:                     # commit the hit
            self.allocator.share(bid)
        priv = self.allocator.allocate(need_private)
        if self.prefix_cache is not None and keys:
            self.prefix_cache.record(shared, len(keys) - shared)
        self.waiting.popleft()
        st.slot = slot
        st.blocks = list(hit_blocks) + priv        # one reference each
        st.table = np.full((self.max_blocks_per_seq,), self.oob_block,
                           np.int32)
        st.table[:shared] = hit_blocks
        tail = total - shared                      # pages past the hit
        st.table[shared:total] = priv[:tail]
        # leftover private blocks are CoW replacements for borrowed
        # pages the prefill will write into (at most one: the last
        # matched page of a fully-cached prompt)
        st.cow_spare = {pg: priv[tail + k]
                        for k, pg in enumerate(range(ro_pages, shared))}
        st.borrowed = set(range(ro_pages, shared))
        st.num_shared = shared
        st.cached_tokens = first_write
        st.kv_len = first_write
        self.slots[slot] = st
        return st

    # -- the running batch -------------------------------------------------

    def active(self) -> List[Tuple[int, RequestState]]:
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    # requires-lock: _lock — advances the _rr round-robin origin
    def plan_spans(self, chunk: int, budget: Optional[int] = None
                   ) -> List[Tuple[int, "RequestState", int, bool]]:
        """Decide each active slot's span for this step: ``(slot, state,
        span_len, is_prefill)``.  Decode slots get their pending token
        plus any speculative draft the engine attached (``st.draft`` —
        span ``1 + len(draft)``, still ≤ chunk by the engine's draft
        cap); prefilling slots split ``budget`` prefill tokens (default:
        no cap) in ≤``chunk`` chunks, round-robined across steps so a
        tight budget starves nobody.  Slots left out idle this step
        (span 0).  The engine runs copy-on-write for spans that land in
        borrowed pages BEFORE materializing the batch arrays
        (span_arrays) — draft positions included."""
        c = int(chunk)
        left = int(budget) if budget is not None else self.max_batch * c
        self._rr = (self._rr + 1) % max(self.max_batch, 1)
        order = sorted(self.active(),
                       key=lambda t: (t[0] - self._rr) % self.max_batch)
        plan = []
        for i, st in order:
            if st.prefilling:
                plen = int(st.request.prompt_ids.size)
                n = min(c, plen - st.kv_len, left)
                if n <= 0:
                    continue                       # budget spent: idle
                left -= n
                plan.append((i, st, n, True))
            else:
                # draft tokens are NOT prefill work: they ride the
                # decode slot's lane for free (the ragged kernel skips
                # dead rows either way) and never touch the budget
                plan.append((i, st, 1 + min(len(st.draft), c - 1), False))
        plan.sort(key=lambda t: t[0])
        return plan

    def span_arrays(self, plan, chunk: int, spec_emit: bool = False):
        """The fixed-shape ragged step inputs for a span plan:
        ``(tokens (B,C), tables (B,MB), starts (B,), lens (B,),
        temps (B,), seeds (B,), emit (B,), adapters (B,))`` as numpy
        arrays.  Idle/empty slots get the inert sentinel values —
        shapes NEVER depend on occupancy (a draft miss is ``len 1``,
        never a new shape; an adapter change is a new VALUE in
        ``adapters``, never a new program).  Call AFTER copy-on-write
        has patched the tables.

        ``seeds``/``emit`` drive the per-emitted-token-index PRNG key
        derivation (``engine._sample``): ``emit[i]`` is the emit index
        of the slot's FIRST sampled position — for the speculative step
        (``spec_emit=True``, which samples every span position) a
        completing prefill span is rebased so its LAST position lands
        on emit index ``len(output_ids)``."""
        b, mb, c = self.max_batch, self.max_blocks_per_seq, int(chunk)
        tokens = np.zeros((b, c), np.int32)
        tables = np.full((b, mb), self.oob_block, np.int32)
        starts = np.zeros((b,), np.int32)
        lens = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b,), np.int32)
        emit = np.zeros((b,), np.int32)
        adapters = np.zeros((b,), np.int32)   # 0 = base no-op slot
        for i, st, n, is_prefill in plan:
            req = st.request
            if is_prefill:
                tokens[i, :n] = req.prompt_ids[st.kv_len:st.kv_len + n]
            else:
                tokens[i, 0] = st.pending_token
                if n > 1:
                    tokens[i, 1:n] = st.draft[:n - 1]
            tables[i] = st.table
            starts[i] = st.kv_len
            lens[i] = n
            temps[i] = req.temperature
            seeds[i] = st.sample_seed
            emit[i] = len(st.output_ids) - \
                ((n - 1) if (spec_emit and is_prefill) else 0)
            adapters[i] = req.adapter_slot
        return tokens, tables, starts, lens, temps, seeds, emit, adapters

    def finish(self, st: RequestState, reason: str) -> None:
        """Release the slot and drop every block reference (shared pages
        decref; private pages return to the free list or, if registered
        in the prefix cache, to the evictable LRU pool)."""
        st.finished = True
        st.finish_reason = reason
        self.release_slot(st)

    def release_slot(self, st: RequestState) -> None:
        """Vacate ``st``'s slot and drop every block reference WITHOUT
        finishing it — the preemption/isolation half of ``finish``.
        Shared pages decref (never touched under other readers); CoW
        spares and private pages return to the pool.  The caller
        requeues the state for restoration."""
        if st.slot is not None:
            self.slots[st.slot] = None
            st.slot = None
        if st.blocks:
            self.allocator.free(st.blocks)
            st.blocks = []
        st.table = None
        st.borrowed = set()
        st.cow_spare = {}
        # unaccepted speculative tokens never outlive the slot: a
        # preempt/finish snapshot carries only accepted state (kv_len
        # covers exactly pending + accepted; the draft was transient)
        st.draft = []

    # requires-lock: _lock
    def requeue(self, st: RequestState, head: bool = False) -> None:
        """Put a preempted/isolated request back on the waiting queue —
        at the head for fault isolation (it was mid-flight; resume
        ASAP), at the tail for front-door preemption (the preemptor is
        already queued ahead of it, plain FIFO restores the victim once
        the pressure passes)."""
        if head:
            self.waiting.appendleft(st)
        else:
            self.waiting.append(st)

    # requires-lock: _lock
    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)
