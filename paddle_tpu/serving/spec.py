"""Self-speculative decoding: n-gram drafting for the ragged serving step.

Decode at small batch is memory-bandwidth-bound — every step streams the
full weight set to emit ONE token per slot.  Speculative decoding spends
spare flops to buy tokens: draft K cheap guesses, score them all in one
forward pass, keep the longest prefix the model agrees with.  This
module is the DRAFTING half (host-side, model-free); the VERIFY half is
the engine's existing unified ragged step, which scores a slot's
``[pending, d_1 .. d_k]`` span exactly like a chunked-prefill segment —
one dispatch, same kernel, same ``(B, C)`` shapes (docs/SERVING.md
"Speculative decoding").

Why n-gram self-drafting first (no second model): serving traffic is
full of local repetition — code, templated prose, quoted context, JSON
— where the request's OWN token history predicts its continuation.  The
proposer keeps, per request, an incremental index of every
``min_ngram..max_ngram``-gram in ``prompt + emitted`` tokens; a draft is
the historical continuation of the longest indexed suffix match.  Cost
per step is O(new tokens · n-gram sizes) dict work and zero device
traffic, so a miss costs (almost) nothing and the engine simply runs
that slot at ``draft_len = 0`` through the same compiled program.

Acceptance is GREEDY in v1: the verified step samples every span
position; the accepted length is the longest prefix where the model's
argmax reproduces the draft, plus one bonus token (the model's own next
token — emitted even on a total miss, so a verify step never does worse
than a plain decode step).  Greedy outputs are therefore TOKEN-IDENTICAL
to the non-speculative engine by construction.  Temperature slots ride
the same program with ``draft_len = 0`` (v1); their sampled streams
stay reproducible either way because the engine derives PRNG keys per
EMITTED-TOKEN INDEX, never per step (``engine._sample``).

Rollback is kv_len bookkeeping ONLY: speculative KV lands in pages the
request already reserved at admission (the draft cap enforces it), so
rejecting ``k - a`` drafts just means not advancing ``kv_len`` past the
accepted prefix — the garbage KV beyond it is overwritten by the next
span and never read (attention masks at ``kv_len``).  No page frees, no
copies, and prefix-cache digests only ever chain over accepted pages
(registration happens at prefill completion, before any drafting).

State is REBUILDABLE by design: the index is a pure function of
``prompt + output_ids``, so preempt→swap→restore snapshots carry no
draft state (unaccepted speculative tokens are excluded because they
are never in ``output_ids``), and a request migrating to another
replica after an evacuation just rebuilds its index lazily on the
destination's proposer.  A rollback that truncated ``output_ids``
(fault isolation) is detected by the consumed-token watermark and the
index is rebuilt from scratch.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Tuple

__all__ = ["NgramProposer"]


class _SpecState:
    """Per-request incremental n-gram index over ``prompt + emitted``."""

    __slots__ = ("ctx", "consumed", "indexed", "index")

    def __init__(self):
        self.ctx: List[int] = []     # prompt + emitted tokens, as ints
        self.consumed = 0            # tokens of (prompt+output) in ctx
        self.indexed = 0             # ngram endings < indexed are in index
        self.index: Dict[Tuple[int, ...], int] = {}   # ngram -> last end pos


class NgramProposer:
    """Suffix-match n-gram draft proposer (one per speculative engine).

    ``propose(st, cap)`` returns up to ``min(depth, cap)`` draft tokens
    for a request state: the tokens that FOLLOWED the most recent
    earlier occurrence of the longest (``max_ngram`` down to
    ``min_ngram``) suffix of the request's context.  Returns ``[]`` on
    a miss — the engine runs the slot at ``draft_len = 0``.

    Retention is bounded: entries drop at request retirement
    (``drop``), and ``max_requests`` LRU-evicts stragglers (a preempted
    request whose entry was evicted rebuilds lazily — correctness never
    depends on the index surviving).
    """

    def __init__(self, depth: int, min_ngram: int = 1, max_ngram: int = 4,
                 max_requests: int = 4096):
        if depth < 1:
            raise ValueError(f"draft depth must be >= 1, got {depth}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.depth = int(depth)
        self.min_ngram = int(min_ngram)
        self.max_ngram = int(max_ngram)
        self.max_requests = int(max_requests)
        self._requests: "collections.OrderedDict[str, _SpecState]" = \
            collections.OrderedDict()
        # lifetime telemetry (Engine.spec_stats / the serve.spec.* twins)
        self.proposed = 0            # draft tokens sent to verification
        self.accepted = 0            # of those, accepted by the model
        self.verifies = 0            # verify spans scored (draft_len > 0)
        self.draft_hits = 0          # propose() calls returning a draft
        self.draft_misses = 0        # propose() calls with no match
        self.errors = 0              # propose() failures (degraded to 0)

    # -- index maintenance -------------------------------------------------

    def _get(self, st) -> _SpecState:
        rid = st.request.request_id
        prompt = st.request.prompt_ids
        plen = int(prompt.size)
        target = plen + len(st.output_ids)
        s = self._requests.get(rid)
        if s is None or s.consumed > target:
            # unknown request (fresh, migrated, or LRU-evicted) or a
            # context that SHRANK (fault-isolation rewind truncated
            # output_ids): rebuild from the authoritative token lists
            s = _SpecState()
            self._requests[rid] = s
        self._requests.move_to_end(rid)
        while len(self._requests) > self.max_requests:
            self._requests.popitem(last=False)
        if s.consumed < target:
            if s.consumed < plen:
                s.ctx.extend(int(t) for t in prompt[s.consumed:])
                s.consumed = plen
            s.ctx.extend(st.output_ids[s.consumed - plen:])
            s.consumed = target
        # index every n-gram ENDING strictly before the last position:
        # the suffix lookup below must only ever match an EARLIER
        # occurrence, so the current suffix is deliberately not indexed
        L = len(s.ctx)
        for p in range(s.indexed, L - 1):
            hi = p + 1
            for n in range(self.min_ngram, self.max_ngram + 1):
                if hi >= n:
                    # FIRST occurrence wins: on looping content the
                    # earliest match leaves the longest historical
                    # continuation to draft from (measured: more
                    # accepted tokens per verify step than most-recent
                    # indexing, which tends to match just behind the
                    # cursor and truncate the draft)
                    s.index.setdefault(tuple(s.ctx[hi - n:hi]), p)
        s.indexed = max(s.indexed, L - 1)
        return s

    # -- the proposer surface ----------------------------------------------

    def propose(self, st, cap: int) -> List[int]:
        """Draft up to ``min(depth, cap)`` tokens for ``st`` (a decode
        slot).  ``cap`` is the engine's budget bound: speculative KV
        must land in the request's already-reserved pages and accepted
        tokens must fit the remaining ``max_new_tokens`` budget."""
        cap = min(int(cap), self.depth)
        if cap < 1:
            return []
        s = self._get(st)
        ctx = s.ctx
        L = len(ctx)
        # longest n-gram with a FULL-depth continuation wins; otherwise
        # the longest continuation any matching n offers (a long match
        # ending near the cursor can only draft a token or two — a
        # shorter suffix matching further back often drafts the whole
        # cap, and the verify pass prices both the same)
        best = None
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            p = s.index.get(tuple(ctx[L - n:]))
            if p is not None:
                cont = list(ctx[p + 1:p + 1 + cap])
                if len(cont) == cap:
                    self.draft_hits += 1
                    return cont
                if best is None or len(cont) > len(best):
                    best = cont
        if best:
            self.draft_hits += 1
            return best
        self.draft_misses += 1
        return []

    def drop(self, request_id: str) -> None:
        """Forget a retired request's index (bounded retention)."""
        self._requests.pop(request_id, None)

    def __len__(self) -> int:
        return len(self._requests)

    def stats(self) -> Dict[str, float]:
        """Lifetime drafting/acceptance counters plus the acceptance
        rate (accepted / proposed draft tokens)."""
        return {"proposed": self.proposed, "accepted": self.accepted,
                "accept_rate": (self.accepted / self.proposed)
                if self.proposed else 0.0,
                "verifies": self.verifies,
                "draft_hits": self.draft_hits,
                "draft_misses": self.draft_misses,
                "errors": self.errors,
                "tracked_requests": len(self._requests)}
