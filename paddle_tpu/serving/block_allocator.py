"""Block allocator + paged KV pools — the serving engine's memory layer.

Reference capability: vLLM-style paged KV management (PAPERS.md "Ragged
Paged Attention" describes the TPU kernel shape this feeds).  The pool
is ONE global ``(num_blocks, page, H_kv, D)`` k/v array pair per decoder
layer; requests own disjoint block-id sets and address them through
per-request block tables, so `max_batch` concurrent sequences share the
HBM a single dense `(B, S_max, ...)` cache would burn on padding.

Invariants (enforced here, relied on by the engine — docs/SERVING.md):

- a block id is owned by at most one request at a time (`allocate` pops
  from the free list, `free` returns; double-free raises);
- the engine reserves ALL blocks a request can ever touch at admission
  (`ceil((prompt + max_new_tokens) / page)`), so a running request can
  never fail mid-decode on pool exhaustion — exhaustion only delays
  admission;
- at drain (no waiting, no active requests) `used_blocks == 0`, checked
  by the `serving-smoke` CI gate.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache"]


class BlockAllocator:
    """Free-list allocation over block ids ``[0, num_blocks)``."""

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be positive, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # pop() takes from the tail → low ids hand out first (stable
        # tests and readable block tables)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._used = set()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._used)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: asked for {n} blocks, "
                f"{len(self._free)} free of {self.num_blocks} — admission "
                "should have gated this request (serving/scheduler.py)")
        ids = [self._free.pop() for _ in range(n)]
        self._used.update(ids)
        return ids

    def free(self, ids: Sequence[int]) -> None:
        for i in ids:
            if i not in self._used:
                raise ValueError(
                    f"double free of KV block {i} — a request's block list "
                    "was reclaimed twice")
            self._used.discard(i)
            self._free.append(i)


class PagedKVCache:
    """Per-layer paged k/v pools + their allocator.

    ``caches`` is a list (one entry per decoder layer) of pool tuples in
    the :mod:`paddle_tpu.incubate.nn.functional` cache-arity convention:
    fp ``(k, v)`` of shape ``(num_blocks, page, H_kv, D)``, or — with
    ``dtype="int8"`` — quantized ``(k_i8, v_i8, k_scale, v_scale)`` with
    per-(slot, position, head) f32 scales, reusing the
    :func:`quantize_kv` formula the dense int8 caches use.  The engine
    donates the whole list through its compiled step and writes the
    returned buffers back here.
    """

    def __init__(self, num_layers: int, num_blocks: int, page_size: int,
                 num_kv_heads: int, head_dim: int, dtype="float32"):
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.page_size = int(page_size)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        shape = (self.num_blocks, self.page_size, self.num_kv_heads,
                 self.head_dim)
        from ..models.generation import _is_int8
        self.quantized = _is_int8(dtype)
        if self.quantized:
            sshape = shape[:3]
            self.caches = [
                (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                 jnp.ones(sshape, jnp.float32), jnp.ones(sshape, jnp.float32))
                for _ in range(self.num_layers)]
        else:
            jdt = jnp.dtype(dtype)
            self.caches = [(jnp.zeros(shape, jdt), jnp.zeros(shape, jdt))
                           for _ in range(self.num_layers)]
        self.allocator = BlockAllocator(self.num_blocks)

    @property
    def oob_block(self) -> int:
        """The out-of-range block-id sentinel: scatters to it DROP, so a
        table row full of it makes a slot's writes inert."""
        return self.num_blocks

    def nbytes(self) -> int:
        per_layer = sum(int(a.size) * a.dtype.itemsize
                        for a in self.caches[0])
        return per_layer * self.num_layers
