"""Block allocator + paged KV pools + prefix cache — the serving
engine's memory layer.

Reference capability: vLLM-style paged KV management with hash-based
prefix caching (PAPERS.md "Ragged Paged Attention" describes the TPU
kernel shape this feeds).  The pool is ONE global
``(num_blocks, page, H_kv, D)`` k/v array pair per decoder layer;
requests address disjoint-or-shared block-id sets through per-request
block tables, so `max_batch` concurrent sequences share the HBM a dense
`(B, S_max, ...)` cache would burn on padding — and requests repeating
the same prompt prefix share the SAME physical blocks.

Block lifecycle (docs/SERVING.md has the diagram)::

    free ──allocate──▶ owned (ref 1) ──share──▶ shared (ref N)
      ▲                    │    ▲                   │
      │                    │    └──── CoW copy ◀────┘  (write to shared)
      │              free/deref
      │                    ▼
      └──evict(LRU)── cached (ref 0, registered, content intact)

Invariants (enforced here, relied on by the engine):

- every live block has refcount >= 1; ``free`` releases ONE reference —
  freeing an unknown id or a block with no outstanding references
  raises instead of silently corrupting the free list;
- a refcount-0 block REGISTERED in the prefix cache keeps its content
  and becomes evictable (LRU); eviction deregisters it before reuse;
- the engine reserves every block a request can ever WRITE at admission
  (cache-hit pages it will only read are borrowed via ``share``), so a
  running request never fails mid-decode on pool exhaustion;
- at drain (no waiting, no active requests) ``used_blocks == 0`` — all
  refcounts back to zero; cached blocks linger only as evictable
  capacity (checked by the `serving-smoke` CI gate).
"""

from __future__ import annotations

import collections
import hashlib
import json
import struct
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..resilience import _state as _rs_state

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "SwapManager"]


class BlockAllocator:
    """Refcounted free-list allocation over block ids ``[0, num_blocks)``
    with an LRU pool of evictable (refcount-0, prefix-cached) blocks."""

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be positive, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # pop() takes from the tail → low ids hand out first (stable
        # tests and readable block tables)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}
        # refcount-0 blocks whose content the prefix cache still indexes,
        # in LRU order (oldest first) — reused only when the free list
        # runs dry, via on_evict so the cache drops its hash entry
        self._evictable: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._cached_key: Dict[int, object] = {}   # block → cache key
        self.on_evict: Optional[Callable[[int, object], None]] = None
        self.evictions = 0

    @property
    def free_blocks(self) -> int:
        """Immediately allocatable blocks (free list + evictable)."""
        return len(self._free) + len(self._evictable)

    @property
    def used_blocks(self) -> int:
        """Blocks with at least one outstanding reference."""
        return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks kept alive by the prefix cache (evictable)."""
        return len(self._evictable)

    def refcount(self, block_id: int) -> int:
        return self._ref.get(int(block_id), 0)

    def can_allocate(self, n: int) -> bool:
        return n <= self.free_blocks

    def allocate(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise RuntimeError(
                f"KV pool exhausted: asked for {n} blocks, "
                f"{self.free_blocks} free of {self.num_blocks} — admission "
                "should have gated this request (serving/scheduler.py)")
        ids = []
        for _ in range(n):
            if self._free:
                i = self._free.pop()
            else:
                # LRU eviction: oldest cached block loses its hash entry
                i, _ = self._evictable.popitem(last=False)
                key = self._cached_key.pop(i)
                self.evictions += 1
                if self.on_evict is not None:
                    self.on_evict(i, key)
            self._ref[i] = 1
            ids.append(i)
        return ids

    def share(self, block_id: int) -> None:
        """Take one more reference on a live or cached block (a prefix-
        cache hit borrowing the block into another request's table).
        Reviving a cached block removes it from the evictable pool but
        keeps its registration — future lookups still hit it."""
        i = int(block_id)
        if i in self._ref:
            self._ref[i] += 1
        elif i in self._evictable:
            del self._evictable[i]
            self._ref[i] = 1
        else:
            raise ValueError(
                f"share of block {i} which is neither live nor cached")

    def free(self, ids: Sequence[int]) -> None:
        """Release ONE reference per id.  A block reaching refcount 0
        returns to the free list — or, if the prefix cache registered
        it, to the evictable LRU pool with its content intact."""
        for i in ids:
            i = int(i)
            if not 0 <= i < self.num_blocks:
                raise ValueError(
                    f"free of unknown KV block {i} — valid ids are "
                    f"[0, {self.num_blocks})")
            if i not in self._ref:
                raise ValueError(
                    f"double free of KV block {i} — a request's block "
                    "list was reclaimed twice, or the id was never "
                    "allocated")
            self._ref[i] -= 1
            if self._ref[i] == 0:
                del self._ref[i]
                if i in self._cached_key:
                    self._evictable[i] = None       # MRU end
                else:
                    self._free.append(i)

    # -- prefix-cache bookkeeping (called by PrefixCache) ------------------

    def _mark_cached(self, block_id: int, key: object) -> None:
        self._cached_key[int(block_id)] = key

    def _is_cached(self, block_id: int) -> bool:
        return int(block_id) in self._cached_key


class PrefixCache:
    """Hash-based prefix cache: page-aligned prompt prefixes → pool
    blocks, with refcounted sharing and LRU eviction (the host half;
    copy-on-write copies run through
    :func:`incubate.nn.functional.paged_copy_blocks`).

    Keys are CHAINED content digests: page ``i``'s key is
    ``blake2b(key[i-1] || tokens[i*page:(i+1)*page])``, so a hit on page
    ``i`` implies every earlier token matches too — one dict probe per
    page, no collision risk at 16-byte digests.  Only FULL prompt pages
    are registered (a partial page's tail would diverge per request).
    """

    def __init__(self, allocator: BlockAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._blocks: Dict[bytes, int] = {}     # key → block id
        self.hits = 0          # pages served from cache
        self.misses = 0        # hashable pages that missed
        allocator.on_evict = self._on_evict

    @staticmethod
    def page_keys(prompt_ids, page_size: int,
                  salt: bytes = b"") -> List[bytes]:
        """Chained digests for every FULL page of ``prompt_ids``.

        ``salt`` seeds the chain: pages written under different salts
        never share, however identical their tokens.  Multi-LoRA uses
        the adapter name here (scheduler.submit) — an adapter's q/k/v
        deltas change the KV CONTENT at every position, so a page
        prefilled under adapter A must never be borrowed by a request
        on adapter B (or the base model), and the chained digest is
        exactly the right place to encode that: one seed, every
        downstream page key diverges."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        keys, prev = [], bytes(salt)
        for p in range(ids.size // page_size):
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(ids[p * page_size:(p + 1) * page_size].tobytes())
            prev = h.digest()
            keys.append(prev)
        return keys

    def lookup(self, keys: Sequence[bytes]) -> List[int]:
        """Block ids for the longest cached prefix of ``keys``.  Pure
        peek — the caller commits the hit with ``allocator.share`` per
        block plus one :meth:`record` call (admission is
        single-threaded, so peek-then-commit is atomic; a blocked
        admission retried every step must not inflate the stats)."""
        out: List[int] = []
        for k in keys:
            bid = self._blocks.get(k)
            if bid is None:
                break
            out.append(bid)
        return out

    def record(self, hits: int, misses: int) -> None:
        """Count one committed admission's page hits/misses."""
        self.hits += int(hits)
        self.misses += int(misses)

    def register(self, key: bytes, block_id: int) -> bool:
        """Index ``block_id`` (a fully-written prompt page owned by the
        caller) under ``key``.  First writer wins: if the key is already
        cached (two identical prompts prefilled concurrently), the
        duplicate block stays a normal private block."""
        if key in self._blocks:
            return False
        self._blocks[key] = int(block_id)
        self.allocator._mark_cached(int(block_id), key)
        return True

    def _on_evict(self, block_id: int, key: object) -> None:
        self._blocks.pop(key, None)

    def __len__(self) -> int:
        return len(self._blocks)

    def stats(self) -> Dict[str, float]:
        probes = self.hits + self.misses
        # "registered_pages" counts hash-indexed pages whether live or
        # evictable — deliberately NOT named like the serve.cached_blocks
        # gauge, which is the refcount-0 evictable pool only
        return {"hits": self.hits, "misses": self.misses,
                "hit_rate": (self.hits / probes) if probes else 0.0,
                "registered_pages": len(self._blocks),
                "evictions": self.allocator.evictions}


class PagedKVCache:
    """Per-layer paged k/v pools + their allocator.

    ``caches`` is a list (one entry per decoder layer) of pool tuples in
    the :mod:`paddle_tpu.incubate.nn.functional` cache-arity convention:
    fp ``(k, v)`` of shape ``(num_blocks, page, H_kv, D)``, or — with
    ``dtype="int8"`` — quantized ``(k_i8, v_i8, k_scale, v_scale)`` with
    per-(slot, position, head) f32 scales, reusing the
    :func:`quantize_kv` formula the dense int8 caches use.  The engine
    donates the whole list through its compiled step and writes the
    returned buffers back here.

    ``mesh``: a serving mesh (``serving.distributed.serving_mesh``) puts
    every pool on the mesh with the KV-HEAD axis sharded over ``mp`` and
    the block axis replicated — block ids and tables stay mesh-invariant
    host integers, so the allocator, prefix cache, and CoW bookkeeping
    are untouched by sharding (docs/SERVING.md "Sharded serving").
    """

    def __init__(self, num_layers: int, num_blocks: int, page_size: int,
                 num_kv_heads: int, head_dim: int, dtype="float32",
                 mesh=None):
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.page_size = int(page_size)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.mesh = mesh
        if mesh is not None:
            # TP pool layout (docs/SERVING.md "Sharded serving"): the KV
            # HEAD axis is split over the mesh's mp axis — each shard
            # holds its heads' slice of EVERY block — while the block
            # axis stays replicated so block ids, tables, and the
            # allocator's host bookkeeping are mesh-invariant.
            if "mp" not in mesh.axis_names:
                raise ValueError(
                    f"serving mesh must carry an 'mp' axis, got "
                    f"{mesh.axis_names} (serving.distributed.serving_mesh)")
            tp = mesh.shape["mp"]
            if self.num_kv_heads % tp:
                raise ValueError(
                    f"num_kv_heads={self.num_kv_heads} not divisible by "
                    f"the mesh's mp degree {tp} — the paged pools shard "
                    "the head axis")
        shape = (self.num_blocks, self.page_size, self.num_kv_heads,
                 self.head_dim)
        from ..models.generation import _is_int8
        self.quantized = _is_int8(dtype)
        if self.quantized:
            sshape = shape[:3]
            self.caches = [
                (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                 jnp.ones(sshape, jnp.float32), jnp.ones(sshape, jnp.float32))
                for _ in range(self.num_layers)]
        else:
            jdt = jnp.dtype(dtype)
            self.caches = [(jnp.zeros(shape, jdt), jnp.zeros(shape, jdt))
                           for _ in range(self.num_layers)]
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            # (num_blocks, page, H_kv[, D]) pools and int8 scale arrays:
            # head axis over mp, everything else replicated.  The spec
            # deliberately omits the trailing dim (jax normalizes output
            # specs that way) so the warmup dispatch and every
            # steady-state dispatch see IDENTICAL input shardings — a
            # trailing-None mismatch would add a second jit-cache entry
            # and break the one-executable contract the serving gates
            # check.
            sharding = NamedSharding(mesh, P(None, None, "mp"))
            self.caches = [tuple(jax.device_put(c, sharding)
                                 for c in layer) for layer in self.caches]
        self.allocator = BlockAllocator(self.num_blocks)

    @property
    def oob_block(self) -> int:
        """The out-of-range block-id sentinel: scatters to it DROP, so a
        table row full of it makes a slot's writes inert."""
        return self.num_blocks

    def nbytes(self) -> int:
        per_layer = sum(int(a.size) * a.dtype.itemsize
                        for a in self.caches[0])
        return per_layer * self.num_layers


class SwapManager:
    """Host-RAM swap space for preempted requests' KV pages.

    The preemption half of the front door (docs/SERVING.md "Front
    door"): instead of rejecting work when the pool is tight, the engine
    picks a victim, ``swap_out``s the content of its allocated pages —
    every layer's k/v rows, and for int8 pools the scale rows too — into
    host numpy buffers, frees the blocks, and later ``swap_in``s the
    bytes into freshly allocated blocks so the request resumes
    token-identical.

    Both directions run through ONE fixed-shape compiled program each (a
    ``(chunk,)``-row gather and a donated scatter), padded with the OOB
    sentinel: gather padding reads a clamped row the host copy discards,
    scatter padding drops (jax OOB-scatter semantics).  Any page count
    rides the same two executables — compiled once at
    ``Engine.warmup()``, zero recompiles under preemption churn (the
    ``chaos-serving`` gate's contract).

    Refcount discipline: swap only COPIES content — shared prefix-cache
    pages a victim borrowed are read, never mutated, so they are never
    swapped out from under the other slots (or cache entries) still
    referencing them; the victim merely drops its references and
    re-materializes private copies at restore.
    """

    def __init__(self, kv: PagedKVCache, chunk: int = 8):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.kv = kv
        self.chunk = int(chunk)
        self.pages_out = 0           # lifetime pages swapped to host
        self.pages_in = 0            # lifetime pages restored

        def gather(caches, ids):
            return [tuple(c[ids] for c in layer) for layer in caches]

        def scatter(caches, ids, payload):
            return [tuple(c.at[ids].set(p) for c, p in zip(layer, pl))
                    for layer, pl in zip(caches, payload)]

        self._gather = jax.jit(gather)
        # pools are donated, same as the engine's step/CoW programs: the
        # engine owns exactly one copy in HBM
        self._scatter = jax.jit(scatter, donate_argnums=(0,))

    def warmup(self) -> "SwapManager":
        """Compile both directions against all-OOB ids (gather rows are
        discarded, scatter rows drop) so preemption traffic compiles
        nothing."""
        ids = jnp.asarray(np.full((self.chunk,), self.kv.oob_block,
                                  np.int32))
        out = self._gather(self.kv.caches, ids)
        jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
        payload = [tuple(jnp.zeros((self.chunk,) + tuple(c.shape[1:]),
                                   c.dtype) for c in layer)
                   for layer in self.kv.caches]
        caches = self._scatter(self.kv.caches, ids, payload)
        jax.block_until_ready(jax.tree_util.tree_leaves(caches)[0])
        self.kv.caches = caches
        return self

    @staticmethod
    def payload_nbytes(host) -> int:
        return sum(int(a.nbytes) for layer in host for a in layer)

    @staticmethod
    def payload_to_bytes(host) -> bytes:
        """Frame a ``swap_out`` payload as one bytes blob: a
        length-prefixed JSON header (per-layer array dtypes + shapes —
        int8 pools carry four arrays per layer, the scale rows
        included) followed by each array's raw bytes in header order.
        This is the WIRE FORMAT the disaggregated KV transport ships
        between hosts (``serving/disagg.py``): ``payload_from_bytes``
        on any engine with the same pool geometry reconstructs a
        payload whose ``swap_in`` scatters byte-identical rows."""
        # dtype by NAME, not .str: custom dtypes (ml_dtypes bfloat16)
        # collapse to an anonymous void under .str ("<V2") and would
        # not round-trip; the registered name does.  Native byte order
        # assumed — the tier is homogeneous hosts.
        header = json.dumps(
            [[{"dtype": np.dtype(a.dtype).name, "shape": list(a.shape)}
              for a in layer] for layer in host]).encode()
        parts = [struct.pack("<I", len(header)), header]
        for layer in host:
            for a in layer:
                parts.append(np.ascontiguousarray(a).tobytes())
        return b"".join(parts)

    @staticmethod
    def payload_from_bytes(data: bytes):
        """Inverse of :meth:`payload_to_bytes`.  The returned arrays are
        read-only views over ``data`` (``swap_in`` only reads them) —
        copy before mutating."""
        (hlen,) = struct.unpack_from("<I", data, 0)
        metas = json.loads(data[4:4 + hlen].decode())
        host, off = [], 4 + hlen
        for layer in metas:
            rows = []
            for m in layer:
                dt = np.dtype(m["dtype"])
                n = int(np.prod(m["shape"])) if m["shape"] else 1
                a = np.frombuffer(data, dtype=dt, count=n,
                                  offset=off).reshape(m["shape"])
                off += n * dt.itemsize
                rows.append(a)
            host.append(tuple(rows))
        if off != len(data):
            raise ValueError(
                f"swap payload framing mismatch: header describes {off} "
                f"bytes, blob carries {len(data)}")
        return host

    def swap_out(self, block_ids: Sequence[int]):
        """Copy ``block_ids``'s rows from every layer's pools to host
        numpy; returns the payload ``swap_in`` takes.  Read-only on
        device state."""
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            fi("serve.swap")
        n = len(block_ids)
        host = [tuple(np.empty((n,) + tuple(c.shape[1:]),
                               np.dtype(c.dtype)) for c in layer)
                for layer in self.kv.caches]
        for lo in range(0, n, self.chunk):
            m = min(self.chunk, n - lo)
            ids = np.full((self.chunk,), self.kv.oob_block, np.int32)
            ids[:m] = np.asarray(block_ids[lo:lo + m], np.int32)
            out = self._gather(self.kv.caches, jnp.asarray(ids))
            for layer, hlayer in zip(out, host):
                for arr, h in zip(layer, hlayer):
                    h[lo:lo + m] = np.asarray(arr)[:m]
        self.pages_out += n
        return host

    def swap_in(self, block_ids: Sequence[int], host) -> None:
        """Scatter a ``swap_out`` payload into ``block_ids`` (freshly
        allocated blocks) across every layer's pools."""
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            fi("serve.swap")
        n = len(block_ids)
        for lo in range(0, n, self.chunk):
            m = min(self.chunk, n - lo)
            ids = np.full((self.chunk,), self.kv.oob_block, np.int32)
            ids[:m] = np.asarray(block_ids[lo:lo + m], np.int32)
            payload = []
            for hlayer in host:
                rows = []
                for h in hlayer:
                    r = h[lo:lo + m]
                    if m < self.chunk:     # pad: OOB rows drop anyway
                        full = np.zeros((self.chunk,) + r.shape[1:],
                                        r.dtype)
                        full[:m] = r
                        r = full
                    rows.append(jnp.asarray(r))
                payload.append(tuple(rows))
            self.kv.caches = self._scatter(self.kv.caches,
                                           jnp.asarray(ids), payload)
        self.pages_in += n
