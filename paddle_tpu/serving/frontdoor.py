"""Multi-tenant SLO front door for the serving engine.

The Engine (engine.py) is a lab-grade batcher: FIFO admission, hard
typed rejection, no notion of who a request belongs to.  ``FrontDoor``
is what a fleet puts in front of it (ROADMAP item 4 — docs/SERVING.md
"Front door"):

- **Per-tenant policy** (:class:`TenantPolicy`): token-bucket rate
  limits (cost = prompt + max_new tokens), a live-request quota, a
  strict priority tier, and a deficit-round-robin weight within the
  tier.
- **Load shedding with typed answers**: a shed request gets an
  :class:`Admission` carrying the reason and a ``retry_after_s``
  estimate — not an exception (an overloaded server answering
  thousands of sheds per second should not pay exception unwinding per
  shed; ``submit(raise_on_shed=True)`` opts into the
  ``serving.errors`` hierarchy instead).  Shedding decisions are driven
  by the live ``serve.*`` telemetry when observability is enabled —
  queue depth, TTFT p95 (``serve.ttft_ms``), KV block occupancy — and
  by the same engine-local signals when it is not.
- **Fairness**: strict priority across tiers (a starving high-priority
  tenant always goes first), weighted deficit round-robin within a tier
  (two equal-priority floods split admissions by their weights instead
  of by arrival order).
- **KV preemption instead of rejection**: when a higher-priority
  request is block-starved at the engine's queue head, the door picks a
  victim (lowest priority, then youngest) and ``Engine.preempt``s it —
  the victim's pages swap to host RAM and it transparently re-admits
  later, token-identical (block_allocator.SwapManager).

Every decision is deterministic given the submission sequence and the
injected ``clock`` — the chaos-serving CI gate and the fairness tests
rely on that.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Set

from .. import observability as obs
from ..observability import _state as _obs_state
from .errors import (AdmissionError, BudgetUnsatisfiable, QueueFull,
                     RateLimited, UnknownAdapter)
from .scheduler import Request, RequestState

__all__ = ["Admission", "FrontDoor", "TenantPolicy", "TokenBucket"]


# requires-lock: _lock — inspects scheduler.waiting
def relieve_block_pressure(engine, priority_of) -> bool:
    """One engine's pool-pressure preemption policy (shared by
    :meth:`FrontDoor._maybe_preempt` and the DP replica set, which
    applies it per replica): when the queue head is BLOCK-starved (a
    slot is free, blocks are not) and outranks a running request,
    preempt one victim — lowest priority first, youngest within a
    priority.  One victim per call: preemption is a pressure valve, not
    a scheduler.  Returns True when a victim was preempted."""
    sch = engine.scheduler
    if not sch.waiting:
        return False
    head = sch.waiting[0]
    if head.swapped is not None:
        # a restore waiting on blocks: preempting someone else to
        # restore a preemptee would thrash
        return False
    if sch._free_slot() is None:
        return False
    if sch.allocator.can_allocate(sch.blocks_needed(head)):
        return False                # it will admit on the next step
    hp = priority_of(head)
    victims = sorted(
        (priority_of(st), -st.submit_t, st.request.request_id)
        for _slot, st in sch.active()
        if priority_of(st) < hp)
    if victims:
        return engine.preempt(victims[0][2], reason="pool_pressure")
    return False


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's admission contract.

    ``priority``: strict tier — all queued work of a higher tier is
    admitted before any lower tier, and under an SLO breach only
    tenants at or above the door's ``slo_priority_floor`` are admitted.
    ``weight``: deficit-round-robin share *within* a tier.
    ``rate_tokens_per_s`` / ``burst_tokens``: token-bucket rate limit
    over the request token cost (prompt + max_new_tokens); None = no
    limit.  ``max_live_requests``: cap on this tenant's queued + active
    requests; None = no quota.  ``adapter``: the tenant's LoRA adapter
    (docs/SERVING.md "Multi-LoRA") — every submission for this tenant
    decodes through that adapter's stacked weights unless the call
    names one explicitly; validated against the engine's
    ``serving.LoRAPool`` at submit (typed
    :class:`~paddle_tpu.serving.errors.UnknownAdapter`)."""

    priority: int = 0
    weight: float = 1.0
    rate_tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None
    max_live_requests: Optional[int] = None
    adapter: Optional[str] = None


class TokenBucket:
    """Deterministic token bucket (``clock`` injectable for tests)."""

    __slots__ = ("rate", "capacity", "level", "clock", "_t")

    def __init__(self, rate: float, capacity: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.capacity = float(capacity)
        self.level = float(capacity)
        self.clock = clock
        self._t = clock()

    def _refill(self) -> None:
        now = self.clock()
        if now > self._t:
            self.level = min(self.capacity,
                             self.level + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, cost: float) -> float:
        """0.0 on success (cost deducted), else seconds until ``cost``
        becomes affordable — inf for a zero-rate bucket OR a cost
        beyond ``capacity`` (the level can never exceed capacity, so a
        finite hint would send the client into an endless retry loop)."""
        self._refill()
        if cost <= self.level + 1e-9:
            self.level -= cost
            return 0.0
        if self.rate <= 0 or cost > self.capacity + 1e-9:
            return float("inf")
        return (cost - self.level) / self.rate


class Admission(NamedTuple):
    """The typed answer to :meth:`FrontDoor.submit` — admitted or shed,
    never an exception (unless ``raise_on_shed``)."""

    admitted: bool
    request_id: Optional[str]
    reason: Optional[str]        # None | "rate_limited" | "quota" |
    #                              "queue_full" | "slo_shed" | "budget" |
    #                              "unknown_adapter" (evicted at pump)
    retry_after_s: Optional[float]


class _Pending(NamedTuple):
    request: Request
    tenant: str
    cost: int                    # prompt + max_new tokens
    submit_t: float              # perf_counter at door submit: TTFT
    #                              must include time queued in the door


class FrontDoor:
    """SLO-aware multi-tenant admission in front of a warmed
    :class:`~paddle_tpu.serving.Engine`.

    ``policies`` maps tenant name → :class:`TenantPolicy`; unknown
    tenants get ``default_policy``.  ``max_queue_depth`` bounds the
    TOTAL queued work (door queues + engine staging); beyond it
    submissions shed with ``reason="queue_full"``.  ``slo_ttft_p95_ms``
    / ``slo_occupancy`` arm telemetry-driven backpressure: when the
    rolling TTFT p95 or the KV-pool occupancy crosses its threshold,
    tenants below ``slo_priority_floor`` shed with
    ``reason="slo_shed"`` until the signal recovers.
    ``enable_preemption`` lets the door preempt lower-priority running
    requests when a higher-priority admission is block-starved.

    The door feeds the engine's FIFO staging queue at most
    ``engine.max_batch`` deep, so ordering decisions stay here — the
    engine only ever sees work the door already sequenced.

    ``engine`` may also be a DP replica set
    (``serving.distributed.EngineReplicaSet``) or a disaggregated one
    (``serving.disagg.DisaggReplicaSet``): the door's policy runs
    unchanged over the set's aggregate surface, the set decides WHICH
    replica each admitted request lands on — for the disaggregated set
    that means the prefill tier, with the prefill→decode handoff
    happening entirely below this admission surface — and
    pool-pressure preemption delegates to its per-replica policy
    (docs/SERVING.md "Sharded serving", "Disaggregated serving").
    """

    def __init__(self, engine, *,
                 policies: Optional[Dict[str, TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None,
                 max_queue_depth: int = 64,
                 slo_ttft_p95_ms: Optional[float] = None,
                 slo_occupancy: Optional[float] = None,
                 slo_priority_floor: int = 1,
                 drr_quantum: int = 32,
                 enable_preemption: bool = True,
                 retry_after_floor_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        self.engine = engine
        self.policies = dict(policies or {})
        self.default_policy = default_policy or TenantPolicy()
        self.max_queue_depth = int(max_queue_depth)
        self.slo_ttft_p95_ms = slo_ttft_p95_ms
        self.slo_occupancy = slo_occupancy
        self.slo_priority_floor = int(slo_priority_floor)
        self.drr_quantum = int(drr_quantum)
        self.enable_preemption = bool(enable_preemption)
        self.retry_after_floor_s = float(retry_after_floor_s)
        self.clock = clock
        # Cross-thread state (HTTP handler threads submit, the
        # engine-loop thread pumps — serving/server.py): guarded by
        # ServingServer._lock; methods marked `# requires-lock:
        # _lock` must be entered with it held (single-threaded
        # drivers satisfy that trivially).  Checked by pdtpu-lint.
        self._queues: Dict[str, "collections.deque[_Pending]"] = \
            {}                                   # guarded_by: _lock
        self._buckets: Dict[str, TokenBucket] = \
            {}                                   # guarded_by: _lock
        self._outstanding: Dict[str, Set[str]] = \
            {}                                   # guarded_by: _lock
        self._deficit: Dict[str, float] = \
            {}                                   # guarded_by: _lock
        self._rr: Dict[int, int] = {}            # guarded_by: _lock
        self.sheds = 0               # lifetime shed count (all reasons)

    # -- policy plumbing ---------------------------------------------------

    def policy(self, tenant: Optional[str]) -> TenantPolicy:
        if tenant is None:
            return self.default_policy
        return self.policies.get(tenant, self.default_policy)

    # requires-lock: _lock — lazily materializes _buckets entries
    def _bucket(self, tenant: str,
                pol: TenantPolicy) -> Optional[TokenBucket]:
        if pol.rate_tokens_per_s is None:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            cap = pol.burst_tokens if pol.burst_tokens is not None \
                else 4.0 * pol.rate_tokens_per_s
            b = self._buckets[tenant] = TokenBucket(
                pol.rate_tokens_per_s, cap, clock=self.clock)
        return b

    # -- live signals (serve.* telemetry when on, engine-local when off) ---

    # requires-lock: _lock
    def queue_depth(self) -> int:
        """Door queues + the engine's staging queue."""
        return sum(len(q) for q in self._queues.values()) \
            + self.engine.scheduler.queue_depth()

    # requires-lock: _lock
    def _total_queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _ttft_p95(self, tenant: Optional[str] = None) -> Optional[float]:
        """Rolling TTFT p95 for the SLO shed decision.  The GLOBAL
        ``serve.ttft_ms`` signal gates: while it is healthy, nobody is
        shed on TTFT.  Once it breaches, the SUBMITTING tenant's own
        aggregate (``serve.tenant[<t>].ttft_ms``, fed by the engine at
        first token) refines the decision — a below-floor tenant whose
        own latency is healthy is not shed for another tenant's breach.
        The global signal must stay the gate: a shed tenant gets no new
        observations of its own, so deciding on the per-tenant window
        alone would freeze a transient spike into a permanent lockout;
        the global window keeps refreshing off admitted traffic and
        un-sheds everyone when the system recovers."""
        reg = obs.get_registry()
        if reg is None:
            return None
        h = reg.get("serve.ttft_ms")
        g = h.percentile(95) if h is not None else None
        if tenant is None or g is None \
                or self.slo_ttft_p95_ms is None \
                or g <= self.slo_ttft_p95_ms:
            return g
        th = reg.get(f"serve.tenant[{tenant}].ttft_ms")
        if th is not None and th.count:
            return th.percentile(95)
        return g

    def _occupancy(self) -> float:
        alloc = self.engine.kv.allocator
        return alloc.used_blocks / max(self.engine.kv.num_blocks, 1)

    # requires-lock: _lock — sums the pending queues
    def _retry_after(self) -> float:
        """Load-proportional retry hint: pending token cost over the
        live aggregate tok/s when telemetry has one, else a queue-depth
        multiple of the floor.  Deterministic given the signals."""
        rate = None
        reg = obs.get_registry()
        if reg is not None:
            g = reg.get("serve.tok_s")
            rate = g.value if g is not None else None
        if rate:
            pending = sum(p.cost for q in self._queues.values() for p in q)
            est = pending / max(float(rate), 1e-6)
        else:
            est = self.retry_after_floor_s * (1 + self.queue_depth())
        return round(max(self.retry_after_floor_s, est), 4)

    # -- admission ---------------------------------------------------------

    def _shed(self, tenant: str, reason: str,
              retry_after_s: Optional[float], raise_on_shed: bool,
              message: str) -> Admission:
        self.sheds += 1
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.shed").inc()
            reg.counter(f"serve.shed[{reason}].count").inc()
        obs.emit_event("serve_shed", tenant=tenant, reason=reason,
                       retry_after_s=retry_after_s)
        if raise_on_shed:
            if reason == "budget":
                raise BudgetUnsatisfiable(message)
            if reason in ("rate_limited", "quota"):
                raise RateLimited(message, retry_after_s or
                                  self.retry_after_floor_s)
            raise QueueFull(message, retry_after_s)
        return Admission(False, None, reason, retry_after_s)

    # requires-lock: _lock — the handler-thread entry point
    def submit(self, prompt_ids, *, tenant: str = "default",
               max_new_tokens: int = 16, temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               on_token: Optional[Callable] = None,
               request_id: Optional[str] = None,
               adapter: Optional[str] = None,
               raise_on_shed: bool = False) -> Admission:
        """Admit or shed one request; always returns an
        :class:`Admission` (malformed requests — empty prompt, bad
        max_new_tokens, duplicate id, an adapter the engine has not
        loaded — still raise, they are caller bugs, not load).
        ``adapter`` overrides the tenant policy's ``adapter`` mapping
        for this one request."""
        pol = self.policy(tenant)
        eng = self.engine
        ad = adapter if adapter is not None else pol.adapter
        if ad is not None:
            # tenant→model mapping validated at the DOOR, before any
            # queueing: a bad mapping answers typed at submit instead of
            # shedding mysteriously at pump time
            pool = getattr(eng, "lora", None)
            if pool is None:
                raise UnknownAdapter(
                    f"tenant {tenant!r} maps to adapter {ad!r} but the "
                    "engine has no LoRA pool (Engine(lora=...))")
            pool.slot_of(ad)          # raises UnknownAdapter if absent
        req = Request(prompt_ids=prompt_ids,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_token_id=eos_token_id, on_token=on_token,
                      request_id=request_id, tenant=tenant, adapter=ad)
        p = int(req.prompt_ids.size)
        cost = p + req.max_new_tokens
        if req.request_id in eng._states or any(
                pnd.request.request_id == req.request_id
                for q in self._queues.values() for pnd in q):
            raise AdmissionError(
                f"request_id {req.request_id!r} is already in use")
        # feasibility bound: the request must fit ONE engine — a replica
        # set exposes its per-replica pool size here, because the summed
        # kv.num_blocks would answer "admitted" for a request no single
        # replica can ever hold (it would then shed silently at pump)
        cap = getattr(eng, "budget_num_blocks", None)
        if cap is None:
            cap = eng.kv.num_blocks
        if cost > eng.max_seq_len or \
                eng.scheduler.blocks_for(cost) > cap:
            return self._shed(
                tenant, "budget", None, raise_on_shed,
                f"prompt {p} + max_new {req.max_new_tokens} can never "
                f"fit this engine (max_seq_len={eng.max_seq_len}, "
                f"{cap} KV blocks)")
        if pol.max_live_requests is not None and \
                self._live_count(tenant) >= pol.max_live_requests:
            return self._shed(
                tenant, "quota", self._retry_after(), raise_on_shed,
                f"tenant {tenant!r} is at its live-request quota "
                f"({pol.max_live_requests})")
        if self.queue_depth() >= self.max_queue_depth:
            return self._shed(
                tenant, "queue_full", self._retry_after(), raise_on_shed,
                f"queue at max_queue_depth={self.max_queue_depth}")
        if pol.priority < self.slo_priority_floor:
            ttft = self._ttft_p95(tenant) \
                if self.slo_ttft_p95_ms is not None else None
            if ttft is not None and ttft > self.slo_ttft_p95_ms:
                return self._shed(
                    tenant, "slo_shed", self._retry_after(),
                    raise_on_shed,
                    f"TTFT p95 {ttft:.1f}ms over SLO "
                    f"{self.slo_ttft_p95_ms}ms; shedding below "
                    f"priority {self.slo_priority_floor}")
            if self.slo_occupancy is not None \
                    and self._occupancy() >= self.slo_occupancy:
                return self._shed(
                    tenant, "slo_shed", self._retry_after(),
                    raise_on_shed,
                    f"KV occupancy {self._occupancy():.2f} over "
                    f"{self.slo_occupancy}; shedding below priority "
                    f"{self.slo_priority_floor}")
        # the token bucket is the LAST gate, so a request shed for any
        # other reason is never charged tokens it got nothing for (a
        # queue_full burst must not morph into a rate_limited lockout)
        bucket = self._bucket(tenant, pol)
        if bucket is not None:
            wait = bucket.try_take(cost)
            if wait == float("inf"):
                # beyond burst capacity: no amount of waiting helps
                return self._shed(
                    tenant, "budget", None, raise_on_shed,
                    f"request cost {cost} tokens exceeds tenant "
                    f"{tenant!r}'s burst capacity {bucket.capacity}")
            if wait > 0:
                wait = round(max(wait, self.retry_after_floor_s), 4)
                return self._shed(
                    tenant, "rate_limited", wait, raise_on_shed,
                    f"tenant {tenant!r} over its token rate "
                    f"({pol.rate_tokens_per_s}/s); retry in {wait}s")
        if ad is not None:
            # hold a door-level reference from ADMISSION (same
            # request-id the engine acquires at add_request, so the
            # overlap is a no-op in the id-keyed set): once answered
            # admitted=True, the adapter cannot be evicted out from
            # under a door-queued request (typed AdapterInUse at the
            # evict) — pump can never strand a vetted request on a
            # vanished adapter
            self.engine.lora.acquire(ad, req.request_id)
        self._queues.setdefault(
            tenant, collections.deque()).append(
                _Pending(req, tenant, cost, time.perf_counter()))
        self._outstanding.setdefault(tenant, set()).add(req.request_id)
        tr = _obs_state.TRACE[0]
        if tr is not None:
            # the trace clock starts HERE: time queued in the door is
            # queue-wait the timeline must attribute (same rule as the
            # submit_t handoff in pump()).  The id comes from the
            # current_trace_id contextvar when a caller (the HTTP
            # server's X-Trace-Id) set one.
            req.trace_id = tr.begin(req.request_id, tenant=tenant,
                                    prompt_len=p,
                                    max_new=req.max_new_tokens)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter(f"serve.tenant[{tenant}].requests").inc()
            reg.gauge("serve.frontdoor_depth").set(self._total_queued())
        self.pump()
        return Admission(True, req.request_id, None, None)

    # requires-lock: _lock
    def _live_count(self, tenant: str) -> int:
        self._gc_outstanding()
        return len(self._outstanding.get(tenant, ()))

    # requires-lock: _lock
    def _gc_outstanding(self) -> None:
        eng = self.engine
        queued = {p.request.request_id
                  for q in self._queues.values() for p in q}
        for rids in self._outstanding.values():
            dead = [r for r in rids if r not in queued
                    and (eng._states.get(r) is None
                         or eng._states[r].finished)]
            for r in dead:
                rids.discard(r)

    # -- scheduling: strict priority tiers + weighted DRR ------------------

    # requires-lock: _lock
    def _engine_room(self) -> bool:
        # queue_depth() == len(waiting) on a plain Engine, and the O(1)
        # aggregate sum on a replica set (whose waiting tuple would be
        # materialized per check otherwise)
        return self.engine.scheduler.queue_depth() < self.engine.max_batch

    # requires-lock: _lock
    def _next_pending(self) -> Optional[_Pending]:
        nonempty = [t for t, q in self._queues.items() if q]
        if not nonempty:
            return None
        tier = max(self.policy(t).priority for t in nonempty)
        tenants = sorted(t for t in nonempty
                         if self.policy(t).priority == tier)
        rr = self._rr.get(tier, 0)
        n = len(tenants)
        # each visit grants quantum*weight deficit; the head admits once
        # its tenant's deficit covers its token cost, so admissions
        # interleave by weight.  Bound: a head costs <= max_seq_len, so
        # within ~cost/quantum visits per tenant someone can pay.
        max_hops = n * (2 + int(self.engine.max_seq_len
                                / max(self.drr_quantum, 1)))
        for hop in range(max_hops):
            t = tenants[(rr + hop) % n]
            q = self._queues[t]
            if not q:
                continue
            pol = self.policy(t)
            self._deficit[t] = self._deficit.get(t, 0.0) \
                + self.drr_quantum * max(pol.weight, 1e-6)
            head = q[0]
            if self._deficit[t] + 1e-9 >= head.cost:
                self._deficit[t] -= head.cost
                q.popleft()
                self._rr[tier] = (rr + hop + 1) % n
                if not q:
                    self._deficit[t] = 0.0   # no banking while idle
                return head
        # unreachable with drr_quantum >= 1 (max_hops covers the largest
        # possible head cost), but never wedge: serve the tier FIFO
        for t in tenants:
            if self._queues[t]:
                return self._queues[t].popleft()
        return None

    # requires-lock: _lock — the loop-thread entry point
    def pump(self) -> int:
        """Feed sequenced work into the engine's staging queue and run
        the preemption policy; returns the number admitted.  Called by
        :meth:`submit` and :meth:`step` — idempotent and cheap when
        there is nothing to do."""
        self._gc_outstanding()
        admitted = 0
        while self._total_queued() and self._engine_room():
            pnd = self._next_pending()
            if pnd is None:
                break
            req = pnd.request
            try:
                self.engine.add_request(
                    req.prompt_ids, max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature,
                    eos_token_id=req.eos_token_id, on_token=req.on_token,
                    request_id=req.request_id, tenant=pnd.tenant,
                    adapter=req.adapter)
            except QueueFull:
                # transient: the engine's own max_queue bound tripped —
                # the request stays OURS (front of its tenant queue) and
                # feeds once the staging drains; it was already answered
                # admitted=True, so it must not be shed as permanent.
                # add_request released the shared id-keyed adapter ref
                # on its way out — re-take it, or the door-queued
                # request loses its evict protection (AdapterInUse)
                if req.adapter is not None:
                    pool = getattr(self.engine, "lora", None)
                    if pool is not None:
                        pool.acquire(req.adapter, req.request_id)
                self._queues[pnd.tenant].appendleft(pnd)
                break
            except AdmissionError as e:
                # an already-vetted request the engine still refused
                # (e.g. an id raced into the retained set): shed it
                # instead of wedging the tenant queue behind it
                self._outstanding.get(pnd.tenant, set()).discard(
                    req.request_id)
                if req.adapter is not None:
                    # the door's admission-time adapter reference must
                    # not outlive the request it protected
                    pool = getattr(self.engine, "lora", None)
                    if pool is not None:
                        pool.release(req.adapter, req.request_id)
                tr = _obs_state.TRACE[0]
                if tr is not None:
                    # the trace begun at door submit must not stay live
                    # forever — tracer retention only reaps DONE traces.
                    # (An id collision shares the rid's trace by
                    # construction; if the colliding request is still
                    # live its trace closes early here — ids are the
                    # caller's uniqueness contract, and bounding the
                    # tracer beats preserving an ambiguous timeline.)
                    tr.retire(req.request_id, reason="shed")
                self._shed(pnd.tenant,
                           "unknown_adapter" if isinstance(
                               e, UnknownAdapter) else "budget",
                           None, False, str(e))
                continue
            # TTFT starts at DOOR submission: time queued here is load
            # the serve.ttft_ms signal (and the SLO shed driven by it)
            # must see
            st = self.engine._states.get(req.request_id)
            if st is not None:
                st.submit_t = pnd.submit_t
            admitted += 1
        if self.enable_preemption:
            self._maybe_preempt()
        reg = obs.get_registry()
        if reg is not None:
            reg.gauge("serve.frontdoor_depth").set(self._total_queued())
        return admitted

    def _priority_of(self, st: RequestState) -> int:
        return self.policy(st.request.tenant).priority

    # requires-lock: _lock — inspects scheduler.waiting
    def _maybe_preempt(self) -> None:
        """Apply :func:`relieve_block_pressure` — directly on a plain
        engine, or delegated when the engine is a replica set
        (``serving.distributed.EngineReplicaSet`` exposes
        ``relieve_pressure`` and applies the policy per healthy
        replica, since each replica's pool starves independently)."""
        relieve = getattr(self.engine, "relieve_pressure", None)
        if relieve is not None:
            relieve(self._priority_of)
            return
        relieve_block_pressure(self.engine, self._priority_of)

    # -- the loop ----------------------------------------------------------

    def has_work(self) -> bool:
        return self._total_queued() > 0 or self.engine.has_work()

    def step(self):
        """One pump + one engine step; returns the engine's events."""
        self.pump()
        return self.engine.step()

    def run(self) -> Dict[str, List[int]]:
        """Drain door + engine; same contract as ``Engine.run()`` —
        {request_id: generated ids} for everything finished since the
        last drain."""
        eng = self.engine
        drained = eng._begin_drain()
        try:
            while self.has_work():
                self.pump()
                if eng.has_work():
                    eng.step()
                elif self._total_queued():
                    break           # safety: cannot make progress
        finally:
            eng._end_drain()
        return drained

    def stream(self):
        """Generator over :class:`TokenEvent`s until door + engine
        drain (submissions may keep arriving mid-stream)."""
        while self.has_work():
            self.pump()
            for ev in self.engine.step():
                yield ev
            if not self.engine.has_work() and not self._total_queued():
                return
