"""Sharded serving: TP paged decode and DP replica routing over the mesh.

The serving stack below this module is single-chip; ``distributed/``
already carries the full hybrid mesh (docs/PARALLELISM.md).  This module
composes them two ways (docs/SERVING.md "Sharded serving"):

**Tensor parallelism** — a model too big for one chip serves through ONE
engine whose compiled step is GSPMD-partitioned over a mesh's ``mp``
axis: :func:`serving_mesh` builds the mesh, ``Engine(mesh=...)`` lands
the parameters sharded by their partition specs
(:func:`shard_serving_params`) and the paged KV pools with the HEAD axis
sharded / the block axis replicated (``block_allocator.PagedKVCache``).
Block ids, tables, the allocator, prefix cache, and CoW bookkeeping are
host integers untouched by sharding, so the whole single-chip contract
carries over: warmup still compiles exactly the same program set (one
step, one CoW, the two swap gather/scatter), churn triggers zero
compiles, greedy outputs stay token-identical to the single-chip
engine.  The model's TP sharding constraints
(``mp_layers.constrain``) are anchored at trace time through
:func:`trace_mesh` — per ENGINE, not through the global fleet state, so
replicas can each trace under their own submesh.

**Data parallelism** — throughput beyond one engine comes from
:class:`EngineReplicaSet`: N independent engines (each single-chip or
TP-sharded on its own submesh, :func:`replica_meshes`) behind the
existing :class:`~paddle_tpu.serving.FrontDoor`.  The set duck-types the
Engine surface the door drives (``add_request``/``step``/``run``/
``has_work``/aggregate scheduler+kv facades), so multi-tenant policy,
shedding, and SLO backpressure stay in the door while THIS class decides
*which replica*:

- **least-loaded dispatch** scored from the live per-replica signals the
  ``serve.*`` telemetry exports — queue depth, free KV blocks, a rolling
  TTFT p95 — engine-local when telemetry is off;
- **prefix-affinity routing**: the chained page digests of the prompt
  (``PrefixCache.page_keys``) are probed against every replica's prefix
  cache, and a repeat tenant pins to the replica already holding its
  pages (a shared system prompt must not re-prefill once per replica);
- **replica-failure handling**: a replica that throws (or an injected
  ``serve.replica`` fault) is marked unhealthy and EVACUATED — running
  requests ride the existing preempt path (KV pages swap to host RAM),
  then every queued/preempted state migrates to a healthy replica whose
  restore path scatters the same bytes into its own pools; greedy
  outputs complete token-identical instead of being dropped.  A hard
  failure (the swap itself dies) falls back to a fresh re-prefill of the
  victim, which under greedy decoding regenerates the same tokens.

Stepping is two-phase (``Engine.step_begin``/``step_finish``): the set
dispatches EVERY healthy replica's compiled step back-to-back, then
finishes them in order, so replica ``j``'s device compute overlaps
replica ``i``'s host bookkeeping and device sync — that overlap is where
the aggregate-throughput win over one replica comes from (the
``serve_dp_agg_tok_s`` bench row and the ``serving-dist`` CI gate).

Telemetry: replica-labelled gauges (``serve.replica[i].free_blocks`` /
``queue_depth`` / ``active``), routed/requeued/failure counters, and
``serve_route`` / ``serve_replica_fail`` events
(``tools/telemetry_report.py`` folds a per-replica table).
"""

from __future__ import annotations

import collections
import contextlib
import warnings
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import observability as obs
from ..observability import _state as _obs_state
from ..distributed import mp_layers
from ..distributed.topology import HybridTopology
from ..resilience import _state as _rs_state
from .block_allocator import PrefixCache
from .errors import AdmissionError, QueueFull
from .frontdoor import relieve_block_pressure

__all__ = ["EngineReplicaSet", "replica_meshes", "serving_mesh",
           "shard_serving_params", "trace_mesh"]

# rolling per-replica TTFT window the router scores p95 over: small
# enough to track load shifts, large enough to ride out one burst
_TTFT_WINDOW = 64


def serving_mesh(tp: int = 1, devices: Optional[Sequence] = None):
    """A serving mesh: the standard hybrid axis order with ``mp=tp`` and
    every other axis degree 1, over ``devices`` (default: the first
    ``tp`` of ``jax.devices()``).  Carrying ALL the standard axis names
    (not just ``mp``) lets the model's existing sharding constraints —
    which mention ``dp``/``sharding`` for activations — apply unchanged
    (docs/PARALLELISM.md)."""
    if devices is None:
        devices = jax.devices()[:tp]
    if len(devices) < tp:
        raise ValueError(
            f"serving_mesh(tp={tp}) needs {tp} devices, got "
            f"{len(devices)}")
    return HybridTopology(mp_degree=tp).build_mesh(devices)


def replica_meshes(n_replicas: int, tp: int = 1,
                   devices: Optional[Sequence] = None):
    """``n_replicas`` disjoint serving meshes of ``tp`` devices each —
    the DP layout: replica ``i`` owns devices ``[i*tp, (i+1)*tp)``."""
    if devices is None:
        devices = jax.devices()
    need = n_replicas * tp
    if len(devices) < need:
        raise ValueError(
            f"replica_meshes({n_replicas}, tp={tp}) needs {need} "
            f"devices, got {len(devices)}")
    return [serving_mesh(tp, devices[i * tp:(i + 1) * tp])
            for i in range(n_replicas)]


@contextlib.contextmanager
def trace_mesh(mesh):
    """Install ``mesh`` as the trace-time mesh the model's TP sharding
    constraints (``mp_layers.constrain``) resolve against — around
    trace-triggering calls only (``Engine.warmup``).  The constraint is
    captured into the jaxpr, so steady-state dispatches never read the
    override; DP replicas therefore trace one at a time under their own
    submesh without touching the global fleet state."""
    prev = mp_layers._MESH_OVERRIDE[0]
    mp_layers._MESH_OVERRIDE[0] = mesh
    try:
        yield
    finally:
        mp_layers._MESH_OVERRIDE[0] = prev


def shard_serving_params(model, params: Dict[str, jax.Array], mesh):
    """Commit a ``serving_params`` dict onto ``mesh``, each array under
    the partition spec its layer declared at creation
    (``create_parameter(partition=...)`` — the same specs the training
    path shards by).  Un-annotated parameters and buffers replicate."""
    meta = model.param_meta()
    out = {}
    for name, arr in params.items():
        part = meta[name].partition if name in meta else None
        if part is None:
            spec = P()
        elif isinstance(part, P):
            spec = part
        else:
            spec = P(*part)
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


# ---------------------------------------------------------------------------
# Engine-surface facades: what FrontDoor reads off its engine, aggregated
# ---------------------------------------------------------------------------

class _AggAllocator:
    """Pool-occupancy view over the HEALTHY replicas' allocators: a
    failed replica's (evacuated, empty) pool must drop out of both the
    numerator and the denominator, or the door's SLO-occupancy
    backpressure deflates exactly when the survivors are saturated."""

    def __init__(self, rs: "EngineReplicaSet"):
        self._rs = rs

    @property
    # requires-lock: _lock — reads the health map
    def used_blocks(self) -> int:
        return sum(r.kv.allocator.used_blocks
                   for r in self._rs._healthy_replicas())

    @property
    # requires-lock: _lock — reads the health map
    def free_blocks(self) -> int:
        return sum(r.kv.allocator.free_blocks
                   for r in self._rs._healthy_replicas())

    # requires-lock: _lock — reads the health map
    def can_allocate(self, n: int) -> bool:
        return any(r.kv.allocator.can_allocate(n)
                   for r in self._rs._healthy_replicas())


class _AggKV:
    """KV-capacity view (``FrontDoor._occupancy`` reads this), healthy
    replicas only — see :class:`_AggAllocator`."""

    def __init__(self, rs: "EngineReplicaSet"):
        self._rs = rs
        self.allocator = _AggAllocator(rs)

    @property
    # requires-lock: _lock — reads the health map
    def num_blocks(self) -> int:
        return sum(r.kv.num_blocks for r in self._rs._healthy_replicas())


class _AggScheduler:
    """Admission-pressure view (``FrontDoor`` room/queue checks)."""

    def __init__(self, rs: "EngineReplicaSet"):
        self._rs = rs

    # requires-lock: _lock — sums the replicas' waiting queues
    def queue_depth(self) -> int:
        return sum(r.scheduler.queue_depth() for r in self._rs.replicas)

    def blocks_for(self, total_len: int) -> int:
        return self._rs.replicas[0].scheduler.blocks_for(total_len)

    def active(self) -> List:
        """All replicas' running (slot, state) pairs — slot indices are
        replica-LOCAL (consumers count entries: the server's /healthz)."""
        return [p for r in self._rs.replicas for p in r.scheduler.active()]


class EngineReplicaSet:
    """N independent serving engines behind one Engine-shaped surface.

    ``engines`` must share geometry (``max_seq_len``, ``page_size``,
    pool dtype/arity) so a preempted request's host payload restores
    into ANY replica's pools — that is what replica-failure migration
    leans on.  Meshes may differ per replica (``replica_meshes``).

    Drive it exactly like an Engine — ``add_request`` routes, ``step``
    dispatches every healthy replica then finishes them in order,
    ``run``/``stream`` drain — or put a :class:`FrontDoor` in front for
    multi-tenant policy; the door's staging, preemption, and drain
    protocols all delegate here unchanged.

    Cross-thread contract: same as the Engine's — behind a
    ``ServingServer``, handler threads route through ``FrontDoor.submit``
    while the loop thread steps, serialized by ``ServingServer._lock``;
    single-threaded drivers hold it trivially (pdtpu-lint
    lock-discipline)."""

    def __init__(self, engines: Sequence, *, prefix_affinity: bool = True):
        engines = list(engines)
        if not engines:
            raise ValueError("EngineReplicaSet needs at least one engine")
        head = engines[0]

        def _pool_dtypes(e):
            # the actual per-layer pool leaf dtypes (covers fp32 vs
            # bf16, and int8 4-tuple arity), not just the quantized flag
            return tuple(str(c.dtype) for c in e.kv.caches[0])

        for i, e in enumerate(engines[1:], start=1):
            same = (e.max_seq_len == head.max_seq_len
                    and e.page_size == head.page_size
                    and e.kv.num_blocks == head.kv.num_blocks
                    and e.kv.num_kv_heads == head.kv.num_kv_heads
                    and e.kv.head_dim == head.kv.head_dim
                    and e.kv.num_layers == head.kv.num_layers
                    and _pool_dtypes(e) == _pool_dtypes(head))
            if not same:
                raise ValueError(
                    f"replica {i} geometry differs from replica 0 — "
                    "failure migration restores one replica's swapped "
                    "pages into another's pools and routing assumes any "
                    "replica can hold any admitted request, so "
                    "max_seq_len, page_size, num_blocks, KV dims and "
                    "pool dtype must match")
        pools = {id(getattr(e, "lora", None)) for e in engines}
        if len(pools) > 1:
            # migration moves RequestStates between replicas WITHOUT
            # re-admission, so Request.adapter_slot must stay valid on
            # the destination — one shared LoRAPool guarantees that
            # (distinct pools could map the same name to different
            # slots, silently decoding with another tenant's weights)
            raise ValueError(
                "replicas of one set must share a single LoRAPool "
                "object (or none) — docs/SERVING.md \"Multi-LoRA\"")
        self.replicas = engines
        self.prefix_affinity = bool(prefix_affinity)
        self.max_seq_len = head.max_seq_len
        self.page_size = head.page_size
        self.kv = _AggKV(self)
        self.scheduler = _AggScheduler(self)
        # Cross-thread state (HTTP handler threads route via
        # FrontDoor.submit while the loop thread steps — serialized by
        # ServingServer._lock; see the class docstring):
        self._placements: Dict[str, int] = {}    # guarded_by: _lock
        self._health: List[bool] = [True] * len(engines)  # guarded_by: _lock
        # rolling TTFT ms per replica: the router-local p95 signal
        # (engine-local so scoring works with telemetry disabled)
        self._ttft = [collections.deque(maxlen=_TTFT_WINDOW)
                      for _ in engines]          # guarded_by: _lock
        self.failures = 0            # lifetime replica failures
        self.requeued = 0            # lifetime requests migrated off a
        #                              failed replica
        # placement entries outlive their engine states only until the
        # next sweep: beyond this bound, step() drops every rid whose
        # state has been evicted (keep_finished), so a long-running
        # router's memory stays bounded like the engines' own retention
        self._placements_cap = 2 * sum(
            e.max_batch + e.keep_finished for e in engines) + 64

    # -- Engine surface ----------------------------------------------------

    def warmup(self) -> "EngineReplicaSet":
        for r in self.replicas:
            r.warmup()
        return self

    @property
    # requires-lock: _lock — merges the replicas' state dicts
    def _states(self):
        return collections.ChainMap(*(r._states for r in self.replicas))

    @property
    def kv_blocks_used(self) -> int:
        return sum(r.kv_blocks_used for r in self.replicas)

    @property
    # requires-lock: _lock — reads the health map
    def max_batch(self) -> int:
        """Healthy staging capacity: the FrontDoor bounds its engine
        staging at this depth, and a failed replica's slots must drop
        out with it — a static all-replicas sum would let the door
        over-stage into the survivors exactly when capacity halved
        (same healthy-only rule as the kv/allocator facades)."""
        return sum(r.max_batch for r in self._healthy_replicas())

    @property
    def budget_num_blocks(self) -> int:
        """The can-this-EVER-fit bound the FrontDoor vets against: one
        replica's pool (geometry is homogeneous), NOT the aggregate —
        a request no single replica can hold must shed up front as
        ``budget``, not be answered admitted and dropped at pump."""
        return self.replicas[0].kv.num_blocks

    @property
    def lora(self):
        """The set's shared LoRAPool (construction enforces one object
        across replicas) — the FrontDoor validates tenant→adapter
        mappings against this, exactly as on a plain Engine."""
        return getattr(self.replicas[0], "lora", None)

    def lora_stats(self):
        """Multi-LoRA pool counters (the shared pool's — not summed)."""
        return self.replicas[0].lora_stats()

    # requires-lock: _lock
    def has_work(self) -> bool:
        return any(r.has_work() for i, r in enumerate(self.replicas)
                   if self._health[i])

    # requires-lock: _lock
    def output_ids(self, request_id: str) -> List[int]:
        return self.replicas[self._placements[request_id]].output_ids(
            request_id)

    def prefix_stats(self) -> Dict[str, float]:
        """Summed prefix-cache counters across replicas."""
        out: Dict[str, float] = {}
        for r in self.replicas:
            for k, v in r.prefix_stats().items():
                if k != "hit_rate":
                    out[k] = out.get(k, 0) + v
        probes = out.get("hits", 0) + out.get("misses", 0)
        out["hit_rate"] = (out.get("hits", 0) / probes) if probes else 0.0
        return out

    def spec_stats(self) -> Dict[str, float]:
        """Summed speculative-decoding counters across replicas
        (docs/SERVING.md "Speculative decoding").  Draft state composes
        with evacuation for free: the n-gram index is a pure function
        of ``prompt + output_ids``, so a request migrating off a failed
        replica rebuilds it lazily on the destination's proposer, and
        preempt→swap→restore snapshots never carry unaccepted
        speculative tokens (they are never in ``output_ids``)."""
        out: Dict[str, float] = {}
        for r in self.replicas:
            for k, v in r.spec_stats().items():
                if k != "accept_rate":
                    out[k] = out.get(k, 0) + v
        prop = out.get("proposed", 0)
        out["accept_rate"] = (out.get("accepted", 0) / prop) if prop \
            else 0.0
        return out

    # requires-lock: _lock
    def preempt(self, request_id: str, **kw) -> bool:
        idx = self._placements.get(request_id)
        if idx is None:
            return False
        return self.replicas[idx].preempt(request_id, **kw)

    # requires-lock: _lock — reads the health map for the door's policy
    def relieve_pressure(self, priority_of) -> None:
        """The FrontDoor's block-pressure preemption, applied per
        healthy replica (each replica's pool starves independently)."""
        for r in self._healthy_replicas():
            relieve_block_pressure(r, priority_of)

    # -- routing -----------------------------------------------------------

    # requires-lock: _lock
    def _healthy_replicas(self):
        return [r for i, r in enumerate(self.replicas) if self._health[i]]

    # requires-lock: _lock
    def _route_candidates(self) -> List[int]:
        """Replica indices admission may route to — every healthy
        replica here; the disaggregated subclass narrows this to the
        prefill tier (``serving/disagg.py``)."""
        return [i for i in range(len(self.replicas)) if self._health[i]]

    # requires-lock: _lock
    def _ttft_p95(self, i: int) -> float:
        win = sorted(self._ttft[i])
        return win[max(0, int(0.95 * len(win)) - 1)] if win else 0.0

    # requires-lock: _lock
    def _load_key(self, i: int):
        """Least-loaded ordering: shortest queue first, most free KV
        blocks next, best rolling TTFT p95 last — the same three
        signals the per-replica ``serve.*`` gauges export."""
        r = self.replicas[i]
        return (r.scheduler.queue_depth(),
                -r.kv.allocator.free_blocks,
                self._ttft_p95(i), i)

    # requires-lock: _lock
    def _pick_replica(self, prompt_ids, adapter=None) -> tuple:
        """(replica index, affinity page hits, page keys) for one
        prompt.  The chained page digests are hashed ONCE here and
        forwarded to the chosen engine's submit, which would otherwise
        re-run the O(prompt) blake2b chain (the PR-5 hash-once rule)."""
        healthy = self._route_candidates()
        if not healthy:
            # typed TRANSIENT rejection, not a plain AdmissionError: the
            # front door's pump would shed that as reason="budget" and
            # silently drop requests it already answered admitted=True.
            # QueueFull keeps them queued at the door (an operator-visible
            # outage, retried if replicas are revived/replaced).
            raise QueueFull(
                "no healthy replicas — every engine in the set has "
                "failed; requests stay queued until the set is revived")
        keys = None
        hits = 0
        if self.prefix_affinity:
            by_hits: Dict[int, int] = {}
            for i in healthy:
                pc = self.replicas[i].prefix_cache
                if pc is None:
                    continue
                if keys is None:
                    # same adapter-salted chain as scheduler.submit:
                    # the affinity probe must see the keys admission
                    # will use, or the pin lands on the wrong replica
                    keys = PrefixCache.page_keys(
                        np.asarray(prompt_ids, np.int32).reshape(-1),
                        self.page_size,
                        salt=adapter.encode() if adapter else b"")
                if keys:
                    by_hits[i] = len(pc.lookup(keys))
            hits = max(by_hits.values()) if by_hits else 0
            if hits > 0:
                pinned = [i for i, h in by_hits.items() if h == hits]
                return min(pinned, key=self._load_key), hits, keys
        return min(healthy, key=self._load_key), 0, keys

    # requires-lock: _lock — the routing entry point (door pump / direct)
    def add_request(self, prompt_ids, **kw) -> str:
        """Route one request to the best healthy replica and queue it
        there.  An injected ``serve.route`` fault surfaces as a typed
        :class:`QueueFull` BEFORE any routing state mutates — the front
        door keeps the request queued and retries next pump."""
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            try:
                fi("serve.route")
            except Exception as e:  # noqa: BLE001
                raise QueueFull(
                    f"routing fault ({type(e).__name__}: {e}) — the "
                    "request stays queued and routes next pump") from e
        rid = kw.get("request_id")
        if rid is not None and rid in self._states:
            raise AdmissionError(
                f"request_id {rid!r} is already in use by a live or "
                "retained request (on any replica)")
        idx, hits, keys = self._pick_replica(prompt_ids,
                                             kw.get("adapter"))
        if keys is not None:
            kw["_page_keys"] = keys
        rid = self.replicas[idx].add_request(prompt_ids, **kw)
        self._placements[rid] = idx
        tr = _obs_state.TRACE[0]
        if tr is not None:
            # trace exists by now (begun in the engine's add_request, or
            # at the door): the routing decision joins its timeline
            tr.point(rid, "route", replica=idx, affinity_hits=hits)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.routed").inc()
            reg.counter(f"serve.replica[{idx}].routed").inc()
        obs.emit_event("serve_route", id=rid, replica=idx,
                       affinity_hits=hits)
        return rid

    # -- stepping ----------------------------------------------------------

    # requires-lock: _lock — the loop-thread entry point
    def step(self) -> List:
        """One step across the set: DISPATCH every healthy replica's
        compiled step back-to-back (``step_begin``), then finish them in
        dispatch order — replica ``j`` computes while replica ``i``
        syncs and does host bookkeeping, which is where the aggregate
        tok/s win over a single replica comes from.

        A replica whose step (or injected ``serve.replica`` fault)
        raises is failed and evacuated: running requests preempt to host
        RAM and migrate, queued ones migrate as-is — nothing is
        dropped."""
        fi = _rs_state.FAULTS[0]
        pendings = []
        for i, r in enumerate(self.replicas):
            if not self._health[i] or not r.has_work():
                continue
            try:
                if fi is not None:
                    fi("serve.replica")
                pendings.append((i, r.step_begin()))
            except Exception as e:  # noqa: BLE001
                self._fail_replica(i, e)
        events: List = []
        for i, pending in pendings:
            r = self.replicas[i]
            try:
                evs = r.step_finish(pending)
            except Exception as e:  # noqa: BLE001
                self._fail_replica(i, e)
                continue
            for ev in evs:
                if ev.finished:
                    st = r._states.get(ev.request_id)
                    if st is not None and st.first_token_t is not None:
                        self._ttft[i].append(
                            (st.first_token_t - st.submit_t) * 1e3)
            events.extend(evs)
        if len(self._placements) > self._placements_cap:
            # keep_finished eviction on the engines has outpaced the
            # routing map: drop placements whose state is gone (queued,
            # active, and retained requests all live in some _states)
            live = self._states
            self._placements = {rid: i for rid, i in
                                self._placements.items() if rid in live}
        reg = obs.get_registry()
        if reg is not None:
            for i, r in enumerate(self.replicas):
                alloc = r.kv.allocator
                reg.gauge(f"serve.replica[{i}].free_blocks").set(
                    alloc.free_blocks)
                reg.gauge(f"serve.replica[{i}].queue_depth").set(
                    r.scheduler.queue_depth())
                reg.gauge(f"serve.replica[{i}].active").set(
                    len(r.scheduler.active()))
        return events

    def stream(self):
        """Generator over token events until the set drains."""
        while self.has_work():
            for ev in self.step():
                yield ev

    # requires-lock: _lock — arms every replica's shared drain capture
    def _begin_drain(self) -> Dict[str, List[int]]:
        """One SHARED drain dict across replicas: each engine's
        finish-time capture writes into it, so the ``run()`` contract
        (complete even past ``keep_finished`` eviction, and across a
        mid-drain replica migration) holds set-wide."""
        drained: Dict[str, List[int]] = {}
        for r in self.replicas:
            for rid, st in r._states.items():
                if st.finished and not st.drained:
                    st.drained = True
                    drained[rid] = list(st.output_ids)
            r._drain_capture = drained
        return drained

    # requires-lock: _lock
    def _end_drain(self) -> None:
        for r in self.replicas:
            r._drain_capture = None

    def run(self) -> Dict[str, List[int]]:
        """Drain every replica; same contract as ``Engine.run()``."""
        drained = self._begin_drain()
        try:
            while self.has_work():
                self.step()
        finally:
            self._end_drain()
        return drained

    # -- replica failure ---------------------------------------------------

    # requires-lock: _lock
    def _fail_replica(self, idx: int, exc: Exception) -> None:
        """Mark replica ``idx`` unhealthy and EVACUATE it: running
        requests ride the existing preempt path (KV pages swap to host
        RAM), then every waiting state — fresh, mid-prefill, or just
        preempted — migrates to a healthy replica, whose restore path
        scatters the same bytes into its own pools.  A hard failure in
        the swap itself degrades that request to a fresh re-prefill
        (greedy decoding regenerates the same tokens)."""
        self._health[idx] = False
        self.failures += 1
        warnings.warn(
            f"serving replica {idx} failed and was evacuated "
            f"({type(exc).__name__}: {exc})", RuntimeWarning,
            stacklevel=3)
        rep = self.replicas[idx]
        tr = _obs_state.TRACE[0]
        for _slot, st in list(rep.scheduler.active()):
            try:
                rep.preempt(st.request.request_id,
                            reason="replica_failure")
            except Exception:  # noqa: BLE001 — hard failure: swap died
                rep.scheduler.release_slot(st)
                self._reset_to_fresh(st)
                rep.scheduler.requeue(st, head=True)
                if tr is not None:
                    # the degraded path: KV gone, prompt re-prefills on
                    # the target — the timeline records it was a reset,
                    # not a byte-exact restore
                    tr.transition(st.request.request_id, "queue",
                                  event="reset_fresh", replica=idx)
        moved = 0
        while rep.scheduler.waiting:
            st = rep.scheduler.waiting.popleft()
            rid = st.request.request_id
            rep._states.pop(rid, None)
            self._evacuate_waiting(idx, st, exc, tr)
            moved += 1
        self.requeued += moved
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.replica_failures").inc()
            reg.counter(f"serve.replica[{idx}].failed").inc()
            if moved:
                reg.counter(f"serve.replica[{idx}].requeued").inc(moved)
        obs.emit_event("serve_replica_fail", replica=idx,
                       exc=type(exc).__name__, message=str(exc)[:200],
                       moved=moved)

    # requires-lock: _lock
    def _evacuate_waiting(self, idx: int, st, exc, tr) -> None:
        """Re-home ONE waiting state popped off failed replica ``idx``
        (already removed from its ``_states``): move it — host payload
        and all — to the least-loaded healthy replica, whose restore
        path scatters the same bytes.  The disaggregated subclass
        overrides this with role-aware routing (swapped decode work
        re-enters the handoff queue; fresh prompts re-route to the
        prefill tier)."""
        rid = st.request.request_id
        try:
            tgt = min((i for i in range(len(self.replicas))
                       if self._health[i]), key=self._load_key)
        except ValueError:
            raise RuntimeError(
                "no healthy replicas left to evacuate onto") from exc
        self.replicas[tgt]._states[rid] = st
        self.replicas[tgt].scheduler.waiting.append(st)
        self._placements[rid] = tgt
        if tr is not None:
            # same trace id before and after: the tracer is keyed by
            # request id and the id rides Request.trace_id, so the
            # migrated state keeps feeding the same timeline
            tr.point(rid, "migrate", from_replica=idx, to_replica=tgt)

    @staticmethod
    def _reset_to_fresh(st) -> None:
        """Rewind a request state to pre-prefill (its KV is gone): the
        degraded path when a failed replica cannot even swap out.  The
        prompt re-prefills on the target replica; already-emitted
        greedy tokens are regenerated identically (temperature > 0
        re-samples), so ``run()``'s finish-time dict stays correct —
        but a STREAMING consumer (``stream()``/``on_token``/SSE) sees
        the regenerated prefix a second time.  The trade for not
        dropping the request; the soft path (swap succeeded) resumes
        mid-sequence and never re-emits."""
        st.swapped = None
        st.kv_len = 0
        st.pending_token = None
        del st.output_ids[:]
        st.text_len = 0
        st.detok_offset = 0
        st.num_shared = 0
        st.num_cowed = 0
        st.cached_tokens = 0
        st.borrowed = set()
        st.cow_spare = {}
