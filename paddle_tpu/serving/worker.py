"""Per-host serving worker: one Engine, one lease, no shared driver.

``python -m paddle_tpu.serving.worker --store=HOST:PORT --role=decode
--factory=pkg.mod:make_engine`` runs the per-host half of the cluster
control plane (``serving/cluster.py``): register with the TCPStore,
renew an epoch-fenced lease, pull admissions / KV-handoff refs /
control commands from this worker's store queues, step the local
Engine, and publish handoffs, outputs and load status back.  The
controller never steps anything — a host failure, GC pause, or upgrade
is confined to this process's failure domain.

Lifecycle (docs/SERVING.md "Cluster serving")::

    register ──► lease renew loop ──► serve (intake/step/publish)
        ▲                                   │
        │        drain (evacuate KV ► evac queue)
        └── re-register ◄── role_flip / rolling_upgrade
                     deregister ◄── drain cmd / SIGTERM

Fencing rules this module owns:

- ``renew_lease`` CAS-chains the lease value; a revoked lease (the
  controller's tombstone) or exhausted retries raise
  :class:`~paddle_tpu.serving.cluster.LeaseLost` — the worker aborts
  its in-flight work WITHOUT publishing, clears engine state, and
  rejoins under a fresh epoch.  A paused-then-resumed process can
  therefore never act on stale ownership: its queue items, commands
  and output writes all carry the old epoch and are dropped/fenced.
- Commands are applied only when their epoch matches; stale ones are
  acked ``stale_epoch`` (``cluster_stale_command``).
- SIGTERM (``launch.PreemptionGuard``) enters the same drain as a
  ``drain`` command: publish finished outputs, hand off / checkpoint
  every live request's KV to the evacuation queue, assert all blocks
  reclaimed, deregister — pages are never stranded.

Fault sites (docs/RESILIENCE.md "Cluster sites"): ``cluster.register``
and ``cluster.lease`` fire inside the retried store transactions;
``cluster.command`` fires before a command applies and requeues it for
the next loop (commands are idempotent per epoch).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import socket
import time
from typing import Callable, List, Optional

from .. import observability as obs
from ..observability.aggregate import registry_to_wire
from ..launch.preempt import PreemptionGuard
from ..resilience import _state as _rs_state
from ..resilience.retry import RetryPolicy
from .cluster import (LeaseLost, StoreQueue, admission_of,
                      admit_admission)
from .disagg import KVHandout, StoreTransport
from .errors import AdmissionError

__all__ = ["ServingWorker", "main"]


class ServingWorker:
    """Drives one Engine against the cluster store.

    Drivable two ways: :meth:`run` is the process loop (subprocess
    workers, with ``PreemptionGuard`` drain on SIGTERM), :meth:`step`
    is one loop iteration (in-process tests interleave worker steps
    with controller pumps deterministically — no threads, no sleeps).

    ``param_source`` (optional ``callable(version) -> params``) is the
    rolling-upgrade hook: the default ``None`` keeps the current params
    (an upgrade is then provably output-identical); production passes a
    checkpoint loader."""

    def __init__(self, engine, store, *, worker_id: Optional[str] = None,
                 prefix: str = "cluster",
                 lease_deadline_s: float = 10.0,
                 lease_interval_s: Optional[float] = None,
                 status_interval_s: float = 0.2,
                 steps_per_poll: int = 4,
                 clock=time.time, retry: Optional[RetryPolicy] = None,
                 transport=None,
                 slo_ttft_p95_ms: Optional[float] = None,
                 param_source: Optional[Callable] = None,
                 version: str = "v0"):
        self.engine = engine
        self.store = store
        self.worker_id = worker_id or \
            f"{socket.gethostname()}-{os.getpid()}"
        self.prefix = prefix.rstrip("/")
        self.role = engine.role
        self.clock = clock
        self.retry = retry if retry is not None else RetryPolicy()
        self.transport = transport if transport is not None else \
            StoreTransport(store, prefix=f"{self.prefix}/xfer")
        self.lease_deadline_s = float(lease_deadline_s)
        self.lease_interval_s = float(lease_deadline_s) / 3.0 \
            if lease_interval_s is None else float(lease_interval_s)
        self.status_interval_s = float(status_interval_s)
        self.steps_per_poll = max(1, int(steps_per_poll))
        self.slo_ttft_p95_ms = slo_ttft_p95_ms
        self.param_source = param_source
        self.version = version
        self.epoch: Optional[int] = None
        self.lease_losses = 0
        self.stale_commands = 0
        self._lease_val: Optional[bytes] = None
        self._last_renew = 0.0
        self._last_status = 0.0
        self._stopping = False
        self._published = set()
        self._pending_cmds: List[dict] = []
        self._xfer_seq = 0
        self._adm_q = self._hoff_q = self._cmd_q = None
        self._rid_seen = set()       # for the exit report's trace audit
        # highest controller epoch observed on any queue item: a
        # fencing token (ClusterController failover) — items stamped
        # below it came from a superseded zombie controller and are
        # dropped, exactly like stale WORKER epochs are
        self._ctl_seen = 0
        # wall-clock offset vs the controller (local − controller),
        # estimated from store round-trips against the controller's
        # published clock key; rides every trace segment so the
        # stitcher can put cross-host timelines on one timebase
        self.clock_offset = 0.0
        self.clock_rtt: Optional[float] = None
        self._trace_seq = 0

    # -- store keys --------------------------------------------------------

    @property
    def _rec_key(self) -> str:
        return f"{self.prefix}/workers/{self.worker_id}"

    @property
    def _lease_key(self) -> str:
        return f"{self.prefix}/lease/{self.worker_id}"

    def _xfer_key(self, rid: str) -> str:
        self._xfer_seq += 1
        return f"{rid}/{self.worker_id}/{self._xfer_seq}"

    # -- membership / lease ------------------------------------------------

    def register(self) -> int:
        """Join (or rejoin) the cluster under a fresh epoch: allocate
        the epoch, write the worker record and the first lease value.
        Retried as one idempotent transaction (a half-applied attempt
        is simply overwritten by the retry's fresh epoch); the
        ``cluster.register`` fault site fires per attempt."""
        def attempt():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("cluster.register")
            epoch = self.store.add(f"{self.prefix}/epoch", 1)
            lease = json.dumps(
                {"epoch": epoch, "t": self.clock()}).encode()
            rec = {"worker": self.worker_id, "role": self.role,
                   "epoch": epoch, "pid": os.getpid(), "state": "up",
                   "version": self.version}
            self.store.set(self._rec_key, json.dumps(rec).encode())
            self.store.set(self._lease_key, lease)
            return epoch, lease

        self.epoch, self._lease_val = self.retry.run(
            attempt, site="cluster.register")
        self._last_renew = self.clock()
        # queue cursors survive a re-register on purpose: items stamped
        # with the old epoch are consumed and dropped as stale, which
        # self-cleans the queues after a flip or rejoin
        if self._adm_q is None:
            base = f"{self.prefix}/q"
            self._adm_q = StoreQueue(self.store,
                                     f"{base}/adm/{self.worker_id}")
            self._hoff_q = StoreQueue(self.store,
                                      f"{base}/hoff/{self.worker_id}")
            self._cmd_q = StoreQueue(self.store,
                                     f"{base}/cmd/{self.worker_id}")
        obs.emit_event("cluster_register", worker=self.worker_id,
                       role=self.role, epoch=self.epoch,
                       version=self.version)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.registers").inc()
        self._sync_clock()
        return self.epoch

    def _sync_clock(self) -> None:
        """Re-estimate ``clock_offset`` against the controller's
        published ``clock`` key: read it between two local clock reads
        and take the midpoint, so half the round-trip cancels.  The
        residual error is bounded by RTT/2 plus the key's staleness
        (the controller re-stamps it every pump).  Runs at registration
        and after each successful lease renewal; one falsy check when
        tracing is disabled — no store traffic, no attribute writes."""
        if obs.get_request_tracer() is None:
            return
        try:
            t0 = self.clock()
            raw = self.store.get(f"{self.prefix}/clock")
            t1 = self.clock()
        except Exception:  # noqa: BLE001 — telemetry must not kill serving
            return
        if raw is None:
            return                   # no controller clock published yet
        try:
            ctl_t = float(json.loads(raw.decode())["t"])
        except (KeyError, TypeError, ValueError, UnicodeDecodeError):
            return
        self.clock_rtt = t1 - t0
        self.clock_offset = (t0 + t1) / 2.0 - ctl_t

    def renew_lease(self) -> None:
        """CAS-chain the lease: expected value is OUR previous write,
        so the controller's revocation tombstone (or any other writer)
        breaks the chain and raises :class:`LeaseLost`.  Transient
        failures retry under the policy (``cluster.lease`` site);
        exhaustion is ALSO a lost lease — the worker cannot know how
        long it was dark, so it must stop acting on the epoch."""
        def attempt():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("cluster.lease")
            new = json.dumps(
                {"epoch": self.epoch, "t": self.clock()}).encode()
            if not self.store.compare_set(self._lease_key,
                                          self._lease_val, new):
                raise LeaseLost(
                    f"worker {self.worker_id!r} lost lease for epoch "
                    f"{self.epoch} (revoked or superseded)")
            return new

        try:
            self._lease_val = self.retry.run(attempt,
                                             site="cluster.lease")
        except LeaseLost:
            raise
        except Exception as e:  # noqa: BLE001 — retries exhausted
            raise LeaseLost(
                f"worker {self.worker_id!r} lease renew exhausted "
                f"retries ({type(e).__name__}: {e})") from e
        self._last_renew = self.clock()
        self._sync_clock()

    def deregister(self, reason: str = "drain") -> None:
        rec = {"worker": self.worker_id, "role": self.role,
               "epoch": self.epoch, "pid": os.getpid(), "state": "left",
               "version": self.version}
        self.store.set(self._rec_key, json.dumps(rec).encode())
        self.store.delete(self._lease_key)
        obs.emit_event("cluster_deregister", worker=self.worker_id,
                       epoch=self.epoch, reason=reason)

    # -- status ------------------------------------------------------------

    def publish_status(self) -> dict:
        eng = self.engine
        reg = obs.get_registry()
        p95 = step_p95 = None
        if reg is not None:
            h = reg.get("serve.ttft_ms")
            if h is not None and h.count:
                p95 = h.percentile(95)
            h = reg.get("serve.step_ms")
            if h is not None and h.count:
                step_p95 = h.percentile(95)
        tel = obs.get_telemetry()
        compiles = tel.sentinel.compiles() \
            if tel is not None and tel.sentinel is not None else None
        cap = getattr(eng, "_slo_capture", None)
        captures = len(cap.captures) if cap is not None \
            and hasattr(cap, "captures") else 0
        breached = bool(captures) or (
            p95 is not None and self.slo_ttft_p95_ms is not None
            and p95 > self.slo_ttft_p95_ms)
        status = {"t": self.clock(), "worker": self.worker_id,
                  "role": self.role, "epoch": self.epoch,
                  "queue_depth": eng.scheduler.queue_depth(),
                  "active": len(eng.scheduler.active()),
                  "free_blocks": eng.kv.allocator.free_blocks,
                  "num_blocks": eng.kv.num_blocks,
                  "handoffs": eng.handoffs,
                  "published": len(self._published),
                  "ttft_p95": p95, "step_p95": step_p95,
                  "compiles": compiles,
                  "clock_offset": self.clock_offset,
                  "slo_breached": breached,
                  "slo_captures": captures}
        self.store.set(f"{self.prefix}/status/{self.worker_id}",
                       json.dumps(status).encode())
        self._last_status = self.clock()
        return status

    def publish_telemetry(self) -> bool:
        """Ship this worker's mergeable registry snapshot (counters /
        gauges / histogram SKETCHES — ``aggregate.registry_to_wire``)
        to ``telemetry/<wid>`` at status cadence; the controller folds
        the fleet's snapshots into per-worker-labelled series and
        merged-sketch rollups for ``GET /metrics``.  One falsy check
        when telemetry is disabled: no snapshot, no store write."""
        reg = obs.get_registry()
        if reg is None:
            return False
        snap = {"t": self.clock(), "worker": self.worker_id,
                "role": self.role, "epoch": self.epoch,
                "clock_offset": self.clock_offset,
                "metrics": registry_to_wire(reg)}
        led = obs.get_ledger()
        if led is not None and led.hbm:
            # live HBM block (engine warmup's pool accounting): the
            # controller folds it into per-worker serve.hbm.* series
            # on the cluster /metrics surface
            snap["hbm"] = led.hbm
        self.store.set(f"{self.prefix}/telemetry/{self.worker_id}",
                       json.dumps(snap).encode())
        return True

    def _publish_trace_segment(self, rid: str, *,
                               close: Optional[str] = None) -> bool:
        """Write this worker's segment of ``rid``'s lifecycle timeline
        to ``trace/<rid>/<wid>:<epoch>:<seq>`` — the cross-host half of
        request tracing.  ``close`` retires the local trace first
        (handoff / evacuation: the request leaves this process
        mid-flight, so the local segment must end at the same point the
        payload ships); retired requests pass ``close=None`` and reuse
        the engine's own retire.  The envelope carries worker / role /
        epoch / ``clock_offset`` so the stitcher can order segments on
        the controller's timebase.  One falsy check when tracing is
        disabled."""
        tr = obs.get_request_tracer()
        if tr is None:
            return False
        if close is not None:
            tr.retire(rid, reason=close)
        t = tr.timeline(rid)
        if t is None:
            return False
        self._trace_seq += 1
        seg = dict(t, id=rid, worker=self.worker_id, role=self.role,
                   epoch=self.epoch, clock_offset=self.clock_offset)
        self.store.set(
            f"{self.prefix}/trace/{rid}/{self.worker_id}:{self.epoch}:"
            f"{self._trace_seq}", json.dumps(seg).encode())
        return True

    # -- intake ------------------------------------------------------------

    def _ctl_fenced(self, item: dict, kind: str) -> bool:
        """Controller-epoch fence: once a queue item from controller
        epoch N is seen, items stamped below N are a superseded
        zombie's late writes — dropped, like stale worker epochs.
        Unstamped items (pre-failover controllers) always pass."""
        ctl = item.get("ctl")
        if ctl is None:
            return False
        if ctl < self._ctl_seen:
            obs.emit_event("cluster_stale_item", kind=kind,
                           worker=self.worker_id, id=item.get("rid")
                           or item.get("id"), ctl=ctl,
                           ctl_seen=self._ctl_seen)
            return True
        self._ctl_seen = ctl
        return False

    def poll_intake(self) -> int:
        """Consume this worker's admission and handoff-ref queues.
        Items stamped with a different epoch were re-routed by the
        controller when the previous incarnation died — drop them;
        items stamped with a superseded CONTROLLER epoch are a zombie
        controller's late writes — drop them too.
        Duplicate request ids (at-least-once re-routes) are skipped."""
        taken = 0
        for adm in self._adm_q.pop_all():
            if adm.get("epoch") != self.epoch:
                obs.emit_event("cluster_stale_item", kind="adm",
                               worker=self.worker_id, id=adm.get("rid"),
                               epoch=adm.get("epoch"))
                continue
            if self._ctl_fenced(adm, "adm"):
                continue
            try:
                admit_admission(self.engine, adm["adm"])
                self._rid_seen.add(adm["rid"])
                taken += 1
            except AdmissionError:
                continue            # already admitted: re-route overlap
        for ref in self._hoff_q.pop_all():
            if ref.get("epoch") != self.epoch:
                obs.emit_event("cluster_stale_item", kind="hoff",
                               worker=self.worker_id, id=ref.get("rid"),
                               epoch=ref.get("epoch"))
                continue
            if self._ctl_fenced(ref, "hoff"):
                continue
            try:
                raw = self.transport.get(ref["xfer"], delete=False)
                self.engine.admit_handout(raw)
                self._rid_seen.add(ref["rid"])
                taken += 1
            except AdmissionError:
                continue
            except Exception as e:  # noqa: BLE001 — hard transfer failure
                # PR-12 degradation rule: the payload is unusable here,
                # so hand the request back as a fresh re-prefill (greedy
                # outputs stay token-identical)
                obs.emit_event("cluster_transfer_failed",
                               worker=self.worker_id, id=ref.get("rid"),
                               exc=type(e).__name__)
                evac = {"rid": ref["rid"], "xfer": None,
                        "adm": ref.get("adm"), "from": self.worker_id}
                StoreQueue(self.store,
                           f"{self.prefix}/q/evac").push(evac)
        return taken

    # -- publish -----------------------------------------------------------

    # the worker loop is the engine's only thread — sole ownership
    # stands in for the lock on every annotated entry point below
    # requires-lock: _lock — drains handed_off/_states
    def publish_handoffs(self) -> int:
        """Stream prefill-complete handoffs: pop the engine's parked
        states, put the ``KVHandout`` payload on the transport, publish
        a ref on the global handoff queue for the controller to route
        to the decode tier.  A hard put failure degrades that request
        to a fresh re-prefill via the evacuation queue."""
        eng = self.engine
        n = 0
        while eng.handed_off:
            st = eng.handed_off.popleft()
            rid = st.request.request_id
            eng._states.pop(rid, None)
            ref = self._snapshot_ref(st)
            q = "q/handoffs" if ref.get("xfer") else "q/evac"
            StoreQueue(self.store, f"{self.prefix}/{q}").push(ref)
            # the request leaves this failure domain here: close the
            # local timeline as a handoff SEGMENT (the decode worker
            # opens the next one under the same trace id off the
            # KVHandout) and publish it for the stitcher
            self._publish_trace_segment(rid, close="handoff")
            n += 1
        return n

    def _snapshot_ref(self, st) -> dict:
        """Package one swapped state as a routable ref: transport
        payload + admission fallback.  Falls back to admission-only
        (fresh re-prefill) when the payload cannot be shipped."""
        rid = st.request.request_id
        adm = admission_of(st.request)
        if st.swapped is not None and st.swapped[0]:
            key = self._xfer_key(rid)
            payload = None
            try:
                payload = KVHandout.from_state(st).to_bytes()
                self.transport.put(key, payload)
                return {"rid": rid, "xfer": key, "nbytes": len(payload),
                        "pages": int(st.swapped[0]),
                        "prefilling": bool(st.prefilling),
                        "adm": adm, "from": self.worker_id}
            except Exception as e:  # noqa: BLE001 — hard put failure
                if payload is not None:
                    self.transport.discard(key, len(payload))
                obs.emit_event("cluster_snapshot_failed",
                               worker=self.worker_id, id=rid,
                               exc=type(e).__name__)
        return {"rid": rid, "xfer": None, "adm": adm,
                "from": self.worker_id}

    # requires-lock: _lock — reads _states (sole-owner worker loop)
    def publish_outputs(self) -> int:
        """Write finished requests' output records (fenced by worker +
        epoch — the controller only accepts the live assignment's
        write)."""
        eng = self.engine
        n = 0
        for rid, st in list(eng._states.items()):
            if not st.finished or rid in self._published:
                continue
            # segment BEFORE the output record: once the controller
            # sees the out, the stitched timeline must already be
            # readable (GET /v1/requests after collect)
            self._publish_trace_segment(rid)
            out = {"tokens": [int(t) for t in st.output_ids],
                   "reason": st.finish_reason,
                   "worker": self.worker_id, "epoch": self.epoch,
                   "tenant": st.request.tenant}
            self.store.set(f"{self.prefix}/out/{rid}",
                           json.dumps(out).encode())
            self._published.add(rid)
            n += 1
        return n

    # -- drain / evacuation ------------------------------------------------

    # requires-lock: _lock — drains waiting/_states (sole-owner loop)
    def drain(self, *, reason: str = "drain") -> int:
        """Evacuate every live request and reclaim every KV block:
        finished outputs publish, parked handoffs stream normally, every
        slotted request preempts (KV pages to host), and each waiting
        state ships as a transport snapshot (token-identical resume —
        ``output_ids`` ride the handout) or, failing that, a fresh
        re-prefill admission.  Post-condition: the allocator is fully
        free and the scheduler empty — the invariant the graceful-
        shutdown regression test pins."""
        eng = self.engine
        self.publish_outputs()
        moved = self.publish_handoffs()
        for st in [s for s in eng.scheduler.slots if s is not None]:
            if st.finished:
                continue
            rid = st.request.request_id
            try:
                eng.preempt(rid, reason=reason)
            except Exception:  # noqa: BLE001 — swap-out exhausted retries
                # pages are unsalvageable: free the slot and fall back
                # to a fresh re-prefill for this request
                eng.scheduler.release_slot(st)
                st.swapped = None
                eng.scheduler.requeue(st)
        snapshots = readmits = 0
        while eng.scheduler.waiting:
            st = eng.scheduler.waiting.popleft()
            rid = st.request.request_id
            eng._states.pop(rid, None)
            if eng.lora is not None and st.request.adapter is not None:
                eng.lora.release(st.request.adapter, rid)
            ref = self._snapshot_ref(st)
            StoreQueue(self.store, f"{self.prefix}/q/evac").push(ref)
            self._publish_trace_segment(rid, close="evacuate")
            if ref.get("xfer"):
                snapshots += 1
            else:
                readmits += 1
            moved += 1
        obs.emit_event("cluster_evacuate", worker=self.worker_id,
                       epoch=self.epoch, reason=reason, moved=moved,
                       snapshots=snapshots, readmits=readmits,
                       free_blocks=eng.kv.allocator.free_blocks,
                       num_blocks=eng.kv.num_blocks)
        reg = obs.get_registry()
        if reg is not None and moved:
            reg.counter("cluster.evacuated").inc(moved)
        return moved

    # requires-lock: _lock — clears waiting/_states (sole-owner loop)
    def _abort_epoch(self) -> None:
        """Lost lease: drop every live request WITHOUT publishing — the
        controller already (or will) re-route them under the fence.
        Blocks are reclaimed locally; nothing leaves this process."""
        eng = self.engine
        for st in [s for s in eng.scheduler.slots if s is not None]:
            eng.scheduler.release_slot(st)
        eng.scheduler.waiting.clear()
        for rid, st in list(eng._states.items()):
            if not st.finished:
                if eng.lora is not None \
                        and st.request.adapter is not None:
                    eng.lora.release(st.request.adapter, rid)
                del eng._states[rid]

    # -- commands ----------------------------------------------------------

    def _ack(self, cmd: dict, *, ok: bool, reason: str = "") -> None:
        self.store.set(f"{self.prefix}/cmdack/{cmd.get('id')}",
                       json.dumps({"ok": ok, "reason": reason,
                                   "worker": self.worker_id}).encode())

    def poll_commands(self) -> None:
        cmds = self._pending_cmds + self._cmd_q.pop_all()
        self._pending_cmds = []
        for cmd in cmds:
            if cmd.get("epoch") != self.epoch:
                self.stale_commands += 1
                obs.emit_event("cluster_stale_command",
                               worker=self.worker_id, id=cmd.get("id"),
                               kind=cmd.get("kind"),
                               epoch=cmd.get("epoch"),
                               current_epoch=self.epoch)
                self._ack(cmd, ok=False, reason="stale_epoch")
                continue
            if self._ctl_fenced(cmd, "cmd"):
                self.stale_commands += 1
                self._ack(cmd, ok=False, reason="stale_ctl")
                continue
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                try:
                    fi("cluster.command")
                except Exception:  # noqa: BLE001 — injected/host fault
                    # requeue: commands are idempotent per epoch, the
                    # next loop iteration re-applies
                    self._pending_cmds.append(cmd)
                    continue
            self.apply_command(cmd)
            if self._stopping:
                break

    def apply_command(self, cmd: dict) -> None:
        kind = cmd.get("kind")
        t0 = self.clock()
        if kind == "drain":
            self.drain(reason="drain")
            self.deregister("drain")
            self._stopping = True
        elif kind == "role_flip":
            # ordering contract (tested): evacuate under the OLD role
            # and epoch first, THEN flip the attribute and re-register —
            # the compiled programs are role-independent, so the flip
            # itself recompiles nothing
            old = self.role
            moved = self.drain(reason="role_flip")
            self.engine.role = cmd["role"]
            self.role = cmd["role"]
            self.register()
            obs.emit_event(
                "cluster_role_flip", worker=self.worker_id,
                role_from=old, role_to=self.role, epoch=self.epoch,
                moved=moved, ms=(self.clock() - t0) * 1000.0)
        elif kind == "rolling_upgrade":
            moved = self.drain(reason="rolling_upgrade")
            if self.param_source is not None:
                self.engine.params = self.param_source(
                    cmd.get("version"))
            self.version = cmd.get("version", self.version)
            self.register()
            obs.emit_event(
                "cluster_upgrade", worker=self.worker_id,
                version=self.version, epoch=self.epoch, moved=moved,
                ms=(self.clock() - t0) * 1000.0)
        else:
            self._ack(cmd, ok=False, reason=f"unknown kind {kind!r}")
            return
        self._ack(cmd, ok=True)

    # -- the loop ----------------------------------------------------------

    def step(self) -> bool:
        """One worker loop iteration: renew the lease when due, apply
        commands, take intake, run up to ``steps_per_poll`` engine
        steps, publish handoffs/outputs/status.  Returns False once the
        worker is stopping.  Raises :class:`LeaseLost` through to the
        caller (``run`` converts it into abort + rejoin; in-process
        tests assert on it directly)."""
        if self._stopping:
            return False
        if self.clock() - self._last_renew >= self.lease_interval_s:
            self.renew_lease()
        self.poll_commands()
        if self._stopping:
            return False
        self.poll_intake()
        eng = self.engine
        for _ in range(self.steps_per_poll):
            if not eng.has_work():
                break
            eng.step()
        self.publish_handoffs()
        self.publish_outputs()
        if self.clock() - self._last_status >= self.status_interval_s:
            self.publish_status()
            self.publish_telemetry()
        return True

    def run(self, *, guard: Optional[PreemptionGuard] = None,
            until: Optional[Callable[["ServingWorker"], bool]] = None,
            idle_s: float = 0.005,
            sleep: Callable[[float], None] = time.sleep) -> None:
        """The process loop: warm up, register, serve until a drain
        command, SIGTERM (graceful drain via ``guard``), or ``until``
        returns True.  A lost lease aborts the epoch and rejoins."""
        if not self.engine._warmed:
            self.engine.warmup()
        if self.epoch is None:
            self.register()
        while not self._stopping:
            if guard is not None and guard.preempted:
                self.drain(reason="sigterm")
                self.deregister("sigterm")
                self._stopping = True
                break
            try:
                self.step()
            except LeaseLost:
                self.lease_losses += 1
                obs.emit_event("cluster_lease_lost",
                               worker=self.worker_id, epoch=self.epoch)
                reg = obs.get_registry()
                if reg is not None:
                    reg.counter("cluster.lease_losses").inc()
                self._abort_epoch()
                self.register()
                continue
            if until is not None and until(self):
                break
            if not self.engine.has_work():
                sleep(idle_s)

    def report(self, *, compiles_baseline: int = 0) -> dict:
        """The exit report the multiprocess tests and the CI gate
        consume (one JSON line on stdout from :func:`main`)."""
        eng = self.engine
        tel = obs.get_telemetry()
        compiles = tel.sentinel.compiles() if tel is not None else None
        tr = obs.get_request_tracer()
        incomplete = []
        if tr is not None:
            for rid in sorted(self._rid_seen | self._published):
                t = tr.timeline(rid)
                if t is not None and not t.get("done") \
                        and rid not in self._published:
                    # undone AND unpublished: fine only if it left this
                    # worker through a handoff/evacuation
                    incomplete.append(rid)
        return {"worker": self.worker_id, "role": self.role,
                "epoch": self.epoch, "version": self.version,
                "compiles_after_warmup":
                    None if compiles is None
                    else compiles - compiles_baseline,
                "free_blocks": eng.kv.allocator.free_blocks,
                "num_blocks": eng.kv.num_blocks,
                "published": sorted(self._published),
                "handoffs": eng.handoffs,
                "lease_losses": self.lease_losses,
                "stale_commands": self.stale_commands,
                "queue_holes": (self._adm_q.holes + self._hoff_q.holes
                                + self._cmd_q.holes)
                if self._adm_q is not None else 0,
                "incomplete_timelines": incomplete,
                # final mergeable registry snapshot: post-mortem fleet
                # accounting works even when the worker died before its
                # last telemetry publish (the fleet-test audit reads it)
                "telemetry": registry_to_wire(reg)
                if (reg := obs.get_registry()) is not None
                else None,
                # memory + compiled-program picture at exit: an on-chip
                # OOM or stall postmortem can say which pool/program
                # owned the bytes without the worker still being alive
                "hbm": led.hbm or None
                if (led := obs.get_ledger()) is not None else None,
                "compiled_artifacts": led.snapshot()
                if led is not None else None,
                "fired": [list(f) for f in getattr(
                    _rs_state.FAULTS[0], "fired", [])]
                if _rs_state.FAULTS[0] is not None else []}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _load_factory(spec: str):
    """``pkg.mod:callable`` or ``path/to/file.py:callable`` — the
    engine factory receives the parsed argparse namespace and returns a
    ready (ideally warmed) Engine."""
    target, _, fn = spec.rpartition(":")
    if not target or not fn:
        raise ValueError(
            f"--factory must be module:callable or file.py:callable, "
            f"got {spec!r}")
    if target.endswith(".py") or os.sep in target:
        name = os.path.splitext(os.path.basename(target))[0]
        loader = importlib.util.spec_from_file_location(name, target)
        mod = importlib.util.module_from_spec(loader)
        loader.loader.exec_module(mod)
    else:
        mod = importlib.import_module(target)
    return getattr(mod, fn)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.worker",
        description="per-host cluster serving worker")
    ap.add_argument("--store", required=True, help="TCPStore HOST:PORT")
    ap.add_argument("--role", default="decode",
                    choices=("prefill", "decode", "both"))
    ap.add_argument("--factory", required=True,
                    help="engine factory: module:callable or "
                         "file.py:callable (receives the args "
                         "namespace, returns an Engine)")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--prefix", default="cluster")
    ap.add_argument("--lease-deadline-s", type=float, default=10.0)
    ap.add_argument("--status-interval-s", type=float, default=0.2)
    ap.add_argument("--steps-per-poll", type=int, default=4)
    ap.add_argument("--slo-ttft-p95-ms", type=float, default=None)
    ap.add_argument("--version", default="v0")
    ap.add_argument("--seed", type=int, default=0,
                    help="forwarded to the factory for model builds")
    args = ap.parse_args(argv)

    from ..launch.store import TCPStore
    from ..resilience import install_faults_from_env

    obs.enable(sinks=[obs.InMemorySink()], crash_hooks=False)
    install_faults_from_env()
    store = TCPStore(args.store, is_master=False,
                     retry=RetryPolicy(max_attempts=5, backoff_s=0.05))
    factory = _load_factory(args.factory)
    engine = factory(args)
    if engine.role != args.role:
        engine.role = args.role
    engine.warmup()
    tel = obs.get_telemetry()
    c0 = tel.sentinel.compiles() if tel is not None else 0
    worker = ServingWorker(
        engine, store, worker_id=args.worker_id, prefix=args.prefix,
        lease_deadline_s=args.lease_deadline_s,
        status_interval_s=args.status_interval_s,
        steps_per_poll=args.steps_per_poll,
        slo_ttft_p95_ms=args.slo_ttft_p95_ms, version=args.version)
    guard = PreemptionGuard()
    with guard:
        worker.run(guard=guard)
    print(json.dumps(worker.report(compiles_baseline=c0)), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
