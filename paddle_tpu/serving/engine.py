"""Continuous-batching serving engine over the paged KV cache.

The throughput story (docs/SERVING.md): instead of one ``generate()``
call per tenant — dense per-sequence caches, per-sequence latency —
``Engine`` keeps ``max_batch`` decode slots running through ONE compiled
decode step and admits/retires requests between steps.  The decode step
reads attention via :func:`incubate.nn.functional.paged_attention`
(Pallas scalar-prefetch kernel on TPU) and appends via the paged scatter
ops, over a global block pool shared by all requests.

Recompile contract: after :meth:`warmup` — one compile for the decode
step + one per prefill bucket — requests of ANY length mix joining and
leaving the batch trigger ZERO further compiles (fixed slot shapes, see
``scheduler.py``; enforced by the ``serving-smoke`` CI gate).

Step anatomy (one :meth:`step` call):

1. **admit**: waiting requests move into free slots while blocks last;
   each admission runs one bucket-padded prefill (writes the prompt's
   KV into its reserved pages, samples the first token → TTFT);
2. **decode**: one compiled step over ALL slots — every active slot's
   pending token is embedded, its KV appended at ``context_len``, paged
   attention over its block table, next token sampled (per-slot
   greedy/temperature);
3. **retire**: EOS / max-token requests leave their slot, their blocks
   return to the free list, callbacks/stream consumers get the tokens.

Telemetry (all zero-overhead when observability is disabled):
``serve.ttft_ms``, ``serve.step_ms``, ``serve.tok_s``,
``serve.queue_depth``, ``serve.kv_blocks_used``, ``serve.active_requests``
+ ``serve_request`` / ``serve_step`` / ``serve_finish`` events and a
``serve.step`` flight-recorder span per step.
"""

from __future__ import annotations

import collections
import time
import traceback
import warnings
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..observability.spans import span
from ..nn.layer import _swapped_params, functional_call, serving_params
from .block_allocator import PagedKVCache
from .scheduler import Request, RequestState, Scheduler

__all__ = ["Engine", "TokenEvent"]

# Incremental detokenization re-runs the tokenizer over a bounded tail
# window of this many tokens (re-anchoring at half-window), keeping
# streaming-text cost linear in output length instead of quadratic.
_DETOK_WINDOW = 64


class TokenEvent(NamedTuple):
    """One emitted token, as returned by ``step()``/``stream()``."""

    request_id: str
    token_id: int
    text: Optional[str]          # incremental detokenized text, if enabled
    finished: bool
    finish_reason: Optional[str]  # "eos" | "length" when finished


def _kv_geometry(model):
    """(num_layers, kv_heads, head_dim) from a CausalLM config."""
    cfg = model.cfg
    kv = getattr(cfg, "num_key_value_heads", None) or \
        cfg.num_attention_heads
    return cfg.num_hidden_layers, kv, cfg.head_dim


def _paged_supported(model) -> bool:
    mdl = getattr(model, "model", None)
    if mdl is None or getattr(model.cfg, "pipeline_stages", 1) != 1:
        return False
    cls = getattr(type(mdl), "decoder_layer_cls", None)
    return cls is not None and getattr(cls, "supports_paged", False)


def _sample(logits, temps, key, step_i):
    """Per-slot greedy (temp==0) or temperature sampling, on device."""
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1)
    k = jax.random.fold_in(key, step_i)
    sampled = jax.random.categorical(
        k, lg / jnp.maximum(temps, 1e-6)[:, None], axis=-1)
    return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)


class Engine:
    """Continuous-batching serving engine (docs/SERVING.md).

    ``model`` is a Llama/GPT-family CausalLM (any config with
    ``supports_paged`` decoder layers and ``pipeline_stages == 1``);
    weights are shared with the dense training/generate() paths via
    ``serving_params``.  ``kv_cache_dtype="int8"`` allocates quantized
    pools (the :func:`quantize_kv` scales, halved KV traffic).

    ``detokenize``: optional ``callable(list[int]) -> str``; when given,
    token events and ``on_token`` callbacks carry the incremental text.
    For streaming it is called on a sliding tail window of the output
    (last ``_DETOK_WINDOW`` tokens), so tokenizers whose suffix output
    differs from the suffix of the full output may see a character-level
    seam at window re-anchors (docs/SERVING.md).

    ``keep_finished``: how many finished requests stay queryable via
    :meth:`output_ids` after completion — older ones are evicted so a
    long-running engine's per-request state stays bounded.
    """

    def __init__(self, model, *, max_batch: int = 8,
                 max_seq_len: int = 256, page_size: int = 16,
                 num_blocks: Optional[int] = None,
                 kv_cache_dtype=None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 detokenize: Optional[Callable] = None, seed: int = 0,
                 keep_finished: int = 1024):
        if not _paged_supported(model):
            raise NotImplementedError(
                f"{type(model).__name__} does not support the paged "
                "serving path (needs supports_paged decoder layers and "
                "pipeline_stages == 1)")
        if max_batch < 1 or max_seq_len < page_size:
            raise ValueError(
                f"bad geometry: max_batch={max_batch}, "
                f"max_seq_len={max_seq_len}, page_size={page_size}")
        max_pos = getattr(model.cfg, "max_position_embeddings", None)
        if max_pos is not None and max_seq_len > max_pos:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the model's "
                f"max_position_embeddings={max_pos}")
        model.eval()
        self.model = model
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.page_size = int(page_size)
        self.max_blocks_per_seq = -(-self.max_seq_len // self.page_size)
        if num_blocks is None:
            # enough for every slot to run a full-length sequence
            num_blocks = self.max_batch * self.max_blocks_per_seq
        n_layers, kv_heads, head_dim = _kv_geometry(model)
        dtype = kv_cache_dtype if kv_cache_dtype is not None else \
            getattr(model.cfg, "dtype", "float32")
        self.kv = PagedKVCache(n_layers, num_blocks, self.page_size,
                               kv_heads, head_dim, dtype=dtype)
        self.scheduler = Scheduler(self.max_batch, self.page_size,
                                   self.max_blocks_per_seq,
                                   self.kv.allocator, self.kv.oob_block)
        self.params = serving_params(model)
        if prefill_buckets is None:
            buckets, b = [], 16
            while b < self.max_seq_len:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_seq_len)
            prefill_buckets = buckets
        self._buckets = sorted(set(int(b) for b in prefill_buckets))
        if self._buckets[-1] > self.max_seq_len:
            raise ValueError(
                f"prefill bucket {self._buckets[-1]} exceeds "
                f"max_seq_len={self.max_seq_len}")
        self._detokenize = detokenize
        self._key = jax.random.key(seed)
        self._step_i = 0
        self._states: Dict[str, RequestState] = {}
        # a long-running engine must not leak one RequestState (plus its
        # token list) per request served: only the `keep_finished` most
        # recently finished requests stay queryable via output_ids()
        self.keep_finished = int(keep_finished)
        self._finished_order: "collections.deque[str]" = collections.deque()
        # set by run() while draining: finish-time output capture that
        # eviction can't outrun (None outside run(), so step()/stream()
        # users accumulate no unbounded side state)
        self._drain_capture: Optional[Dict[str, List[int]]] = None
        self._build_fns()

    # -- compiled paths ----------------------------------------------------

    def _build_fns(self):
        model = self.model

        def _logits_of(params, hidden):
            with _swapped_params(model, params):
                return model.logits(hidden)[:, 0]

        def decode_fn(params, caches, tokens, tables, lens, temps, key,
                      step_i):
            mp = {k[len("model."):]: v for k, v in params.items()
                  if k.startswith("model.")}
            hidden, caches = functional_call(
                model.model, mp, tokens[:, None], caches=caches,
                seq_lens=lens, block_tables=tables, training=False)
            lg = _logits_of(params, hidden[:, -1:])
            return _sample(lg, temps, key, step_i), caches

        def prefill_fn(params, caches, ids, tables, plens, temps, key,
                       step_i):
            mp = {k[len("model."):]: v for k, v in params.items()
                  if k.startswith("model.")}
            hidden, caches = functional_call(
                model.model, mp, ids, caches=caches, seq_lens=plens,
                block_tables=tables, training=False)
            # the LAST REAL token's hidden state, not the padded tail's
            idx = (plens - 1)[:, None, None]
            h_last = jnp.take_along_axis(hidden, idx, axis=1)
            lg = _logits_of(params, h_last)
            return _sample(lg, temps, key, step_i), caches

        # pools are donated: the engine owns exactly one copy in HBM
        self._decode_fn = jax.jit(decode_fn, donate_argnums=(1,))
        self._prefill_fn = jax.jit(prefill_fn, donate_argnums=(1,))

    def _bucket_for(self, prompt_len: int) -> int:
        for b in self._buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket ({self._buckets[-1]})")

    def warmup(self) -> "Engine":
        """Compile the decode step and every prefill bucket up front.

        Uses all-out-of-range block tables, so the warmup traffic's
        writes are dropped — no allocator interaction, no pool pollution.
        After this, serving traffic compiles NOTHING (the serving-smoke
        gate's contract)."""
        with span("serve.warmup"):
            b, mb = self.max_batch, self.max_blocks_per_seq
            oob = np.full((b, mb), self.kv.oob_block, np.int32)
            step0 = jnp.asarray(np.int32(0))
            nxt, caches = self._decode_fn(
                self.params, self.kv.caches,
                jnp.asarray(np.zeros((b,), np.int32)), jnp.asarray(oob),
                jnp.asarray(np.zeros((b,), np.int32)),
                jnp.asarray(np.zeros((b,), np.float32)),
                self._key, step0)
            jax.block_until_ready(nxt)
            self.kv.caches = caches
            for bucket in self._buckets:
                nxt, caches = self._prefill_fn(
                    self.params, self.kv.caches,
                    jnp.asarray(np.zeros((1, bucket), np.int32)),
                    jnp.asarray(oob[:1]),
                    jnp.asarray(np.ones((1,), np.int32)),
                    jnp.asarray(np.zeros((1,), np.float32)),
                    self._key, step0)
                jax.block_until_ready(nxt)
                self.kv.caches = caches
        return self

    # -- request lifecycle -------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens: int = 16,
                    temperature: float = 0.0,
                    eos_token_id: Optional[int] = None,
                    on_token: Optional[Callable] = None,
                    request_id: Optional[str] = None) -> str:
        """Queue one request; returns its id.  The request joins the
        running batch at the next ``step()`` with a free slot and enough
        free blocks for its WHOLE budget (prompt + max_new_tokens)."""
        req = Request(prompt_ids=prompt_ids,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_token_id=eos_token_id, on_token=on_token,
                      request_id=request_id)
        if req.request_id in self._states:
            # a silent overwrite would orphan the first request's slot /
            # blocks bookkeeping and lose its output
            raise ValueError(
                f"request_id {req.request_id!r} is already in use by a "
                "live or retained request")
        p = int(req.prompt_ids.size)
        if p + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds max_seq_len={self.max_seq_len}")
        need = self.scheduler.blocks_for(p + req.max_new_tokens)
        if need > self.kv.num_blocks:
            # an unsatisfiable reservation would sit at the queue head
            # forever and make run()/stream() spin — reject it up front
            raise ValueError(
                f"request needs {need} KV blocks (prompt {p} + "
                f"max_new_tokens {req.max_new_tokens} @ page "
                f"{self.page_size}) but the pool has only "
                f"{self.kv.num_blocks} — raise num_blocks or lower the "
                "budget")
        self._bucket_for(p)   # validates against the bucket ladder
        st = self.scheduler.submit(req)
        self._states[req.request_id] = st
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.requests").inc()
            reg.gauge("serve.queue_depth").set(self.scheduler.queue_depth())
        return req.request_id

    def output_ids(self, request_id: str) -> List[int]:
        return list(self._states[request_id].output_ids)

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    @property
    def kv_blocks_used(self) -> int:
        return self.kv.allocator.used_blocks

    # -- the loop ----------------------------------------------------------

    def _run_prefill(self, st: RequestState, events: List[TokenEvent]):
        req = st.request
        p = int(req.prompt_ids.size)
        bucket = self._bucket_for(p)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :p] = req.prompt_ids
        # device_put of ready numpy arrays only: jnp.asarray of a Python
        # list/scalar traces a tiny program whose one-off compile would
        # break the zero-compiles-after-warmup contract
        nxt, caches = self._prefill_fn(
            self.params, self.kv.caches, jnp.asarray(ids),
            jnp.asarray(st.table[None]),
            jnp.asarray(np.asarray([p], np.int32)),
            jnp.asarray(np.asarray([req.temperature], np.float32)),
            self._key, jnp.asarray(np.int32(self._step_i)))
        self.kv.caches = caches
        self._step_i += 1
        # np.asarray is the device sync: JAX dispatch is async, so the
        # clock must stop AFTER the first token materializes or TTFT
        # reports queueing overhead instead of time-to-first-token
        nxt_tok = int(np.asarray(nxt)[0])
        st.kv_len = p
        st.first_token_t = time.perf_counter()
        reg = obs.get_registry()
        if reg is not None:
            reg.histogram("serve.ttft_ms").observe(
                (st.first_token_t - st.submit_t) * 1e3)
        obs.emit_event("serve_request", id=req.request_id, prompt_len=p,
                       bucket=bucket, slot=st.slot,
                       blocks=len(st.blocks))
        self._emit(st, nxt_tok, events)

    def _emit(self, st: RequestState, token: int,
              events: List[TokenEvent]):
        req = st.request
        st.output_ids.append(token)
        text = None
        if self._detokenize is not None:
            # linear-cost streaming: detokenize only a bounded tail
            # window, emit its growth, and re-anchor at half-window so
            # per-token work never scales with the full output length
            w = st.detok_offset
            full = self._detokenize(list(st.output_ids[w:]))
            text = full[st.text_len:]
            st.text_len = len(full)
            if len(st.output_ids) - w >= _DETOK_WINDOW:
                st.detok_offset = len(st.output_ids) - _DETOK_WINDOW // 2
                st.text_len = len(self._detokenize(
                    list(st.output_ids[st.detok_offset:])))
        done_eos = (req.eos_token_id is not None
                    and token == req.eos_token_id)
        done_len = len(st.output_ids) >= req.max_new_tokens
        if done_eos or done_len:
            self.scheduler.finish(st, "eos" if done_eos else "length")
            if self._drain_capture is not None:
                # BEFORE the eviction below: when more requests than
                # keep_finished retire in one step, the state may be
                # gone by the time run() sees the events
                self._drain_capture[req.request_id] = list(st.output_ids)
                st.drained = True
            self._finished_order.append(req.request_id)
            while len(self._finished_order) > self.keep_finished:
                self._states.pop(self._finished_order.popleft(), None)
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("serve.finished").inc()
            obs.emit_event(
                "serve_finish", id=req.request_id,
                reason=st.finish_reason, tokens=len(st.output_ids),
                ms=round((time.perf_counter() - st.submit_t) * 1e3, 3))
        else:
            st.pending_token = token
        events.append(TokenEvent(req.request_id, token, text, st.finished,
                                 st.finish_reason))
        if req.on_token is not None:
            try:
                req.on_token(req.request_id, token, text)
            except Exception:
                # a raising callback must not tear down the whole step:
                # the batch's other requests already produced events this
                # step and their consumers would silently lose them
                warnings.warn(
                    f"on_token callback for request "
                    f"{req.request_id!r} raised; continuing "
                    f"({traceback.format_exc(limit=3).strip()})",
                    RuntimeWarning, stacklevel=2)

    def step(self) -> List[TokenEvent]:
        """Admit what fits, run one decode step, retire what finished.
        Returns the tokens emitted (one per prefilled/active request)."""
        t0 = time.perf_counter()
        events: List[TokenEvent] = []
        with span("serve.step", emit=False):
            while True:
                st = self.scheduler.admit_next()
                if st is None:
                    break
                self._run_prefill(st, events)
            active = self.scheduler.active()
            if active:
                tokens, tables, lens, temps = self.scheduler.batch_arrays()
                nxt, caches = self._decode_fn(
                    self.params, self.kv.caches, jnp.asarray(tokens),
                    jnp.asarray(tables), jnp.asarray(lens),
                    jnp.asarray(temps), self._key,
                    jnp.asarray(np.int32(self._step_i)))
                self.kv.caches = caches
                self._step_i += 1
                nxt = np.asarray(nxt)
                for i, st in active:
                    st.kv_len += 1   # the pending token's KV just landed
                    self._emit(st, int(nxt[i]), events)
        n_tok = len(events)
        dt = time.perf_counter() - t0
        reg = obs.get_registry()
        if reg is not None and n_tok:
            reg.counter("serve.tokens").inc(n_tok)
            reg.gauge("serve.tok_s").set(round(n_tok / max(dt, 1e-9), 1))
            reg.gauge("serve.queue_depth").set(self.scheduler.queue_depth())
            reg.gauge("serve.kv_blocks_used").set(
                self.kv.allocator.used_blocks)
            reg.gauge("serve.active_requests").set(
                len(self.scheduler.active()))
            reg.histogram("serve.step_ms").observe(dt * 1e3)
        if n_tok:
            obs.emit_event("serve_step", ms=round(dt * 1e3, 3),
                           tokens=n_tok,
                           active=len(self.scheduler.active()),
                           queue=self.scheduler.queue_depth(),
                           kv_blocks_used=self.kv.allocator.used_blocks)
        return events

    def stream(self):
        """Generator: run ``step()`` until drained, yielding each
        :class:`TokenEvent` as it is produced.  More requests may be
        added while streaming — they join the running batch."""
        while self.has_work():
            for ev in self.step():
                yield ev

    def run(self) -> Dict[str, List[int]]:
        """Drain everything; returns {request_id: generated token ids}
        for every request finished since the last ``run()`` — including
        (still-retained) requests that finished during manual ``step()``
        calls before this one (staggered admission).  Outputs are
        captured at finish time, so the dict is complete even when more
        than ``keep_finished`` requests retire in one drain."""
        drained: Dict[str, List[int]] = {}
        for rid, st in self._states.items():
            if st.finished and not st.drained:
                st.drained = True
                drained[rid] = list(st.output_ids)
        self._drain_capture = drained
        try:
            while self.has_work():
                self.step()
        finally:
            self._drain_capture = None
        return drained
