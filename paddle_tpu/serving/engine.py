"""Continuous-batching serving engine over the paged KV cache.

The throughput story (docs/SERVING.md): instead of one ``generate()``
call per tenant — dense per-sequence caches, per-sequence latency —
``Engine`` keeps ``max_batch`` slots running through ONE compiled ragged
step and admits/retires requests between steps.  Every step dispatches a
single fixed-shape batch of per-slot token SPANS — chunked-prefill
segments and single decode tokens side by side — through
:func:`incubate.nn.functional.ragged_paged_attend` (the ragged Pallas
kernel on TPU, the XLA gather fallback elsewhere), over a global block
pool shared by all requests.  Repeated prompt prefixes share physical
blocks via the hash-based prefix cache (block_allocator.PrefixCache):
admission maps hit pages into the new table, reserves only the
remainder, and the step copy-on-writes any borrowed page before writing
into it.

Recompile contract: after :meth:`warmup` — ONE compile for the unified
step plus one for the CoW page-copy helper — requests of ANY length mix
joining and leaving the batch trigger ZERO further compiles (fixed span
shapes, see ``scheduler.py``; enforced by the ``serving-smoke`` CI gate).
Chunked prefill is what keeps that single shape honest: a 2k-token
prompt and a decode token ride the same ``(B, C)`` dispatch, so heavy
admission can no longer stall decode behind per-bucket prefill programs
(head-of-line TTFT — the "Ragged Paged Attention" design, PAPERS.md).

Step anatomy (one :meth:`step` call):

1. **admit**: waiting requests move into free slots while blocks last;
   prefix-cache hits skip straight to their first uncached token;
2. **plan + CoW**: each active slot gets its span (next prefill chunk,
   bounded by the per-step token budget, or its pending decode token);
   spans landing in borrowed pages trigger the copy-on-write dispatch;
3. **one ragged step**: every span's KV is scattered at its positions,
   every query row attends its prefix, one token is sampled per slot —
   consumed only by slots that completed their prompt (TTFT) or decoded;
4. **retire**: EOS / max-token requests leave their slot; their private
   full-prompt pages stay indexed in the prefix cache (evictable LRU),
   everything else returns to the free list.

Robustness (docs/SERVING.md "Front door", docs/RESILIENCE.md):
:meth:`preempt` swaps a running request's KV pages to host RAM
(``SwapManager``) instead of rejecting new work, and re-admission
restores it token-identical; a host-side failure in one request's
bookkeeping — or an injected fault at the ``serve.admit`` /
``serve.prefill`` / ``serve.step`` / ``serve.cow`` / ``serve.swap``
sites — is confined to THAT request (rewind → preempt → re-admit),
never tearing down the compiled step or the other slots.  Admission
rejections are typed (``serving.errors``).  ``serving.FrontDoor``
layers multi-tenant SLO admission on top.

Telemetry (all zero-overhead when observability is disabled):
``serve.ttft_ms``, ``serve.step_ms``, ``serve.tok_s``,
``serve.queue_depth``, ``serve.kv_blocks_used``, ``serve.active_requests``,
``serve.ragged_occupancy``, ``serve.prefix_hits``/``misses``,
``serve.shared_blocks``, ``serve.cached_blocks``, ``serve.cow_copies``,
``serve.preemptions``/``restores``/``swapped_pages``/
``isolated_failures``, and — with speculative decoding on —
``serve.spec.proposed``/``accepted``/``draft_errors`` +
``serve.spec.accept_len``
+ ``serve_request`` / ``serve_step`` / ``serve_finish`` /
``serve_preempt`` / ``serve_restore`` / ``serve_isolated_failure``
events and ``serve.step`` / ``serve.step.finish`` flight-recorder spans
per step (dispatch and sync/post-processing phases).  With request
tracing on, every lifecycle transition additionally feeds the
per-request timeline (``observability/trace.py``: submit → admit →
prefill chunks → first token → preempt/restore → retire, with exact
queue/prefill/decode phase accounting) plus the ``serve.queue_ms`` /
``serve.prefill_ms`` / ``serve.decode_ms_per_token`` histograms and
their ``serve.tenant[<t>].*`` twins — docs/OBSERVABILITY.md "Tracing a
request".
"""

from __future__ import annotations

import collections
import contextlib
import time
import traceback
import warnings
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..observability import _state as _obs_state
from ..observability.spans import span
from ..nn.layer import _swapped_params, functional_call, serving_params
from ..resilience import _state as _rs_state
from ..resilience.retry import RetryPolicy
from .block_allocator import PagedKVCache, PrefixCache, SwapManager
from .errors import (AdmissionError, BudgetUnsatisfiable, QueueFull,
                     UnknownAdapter)
from .scheduler import Request, RequestState, Scheduler

__all__ = ["Engine", "TokenEvent"]

# Incremental detokenization re-runs the tokenizer over a bounded tail
# window of this many tokens (re-anchoring at half-window), keeping
# streaming-text cost linear in output length instead of quadratic.
_DETOK_WINDOW = 64


class TokenEvent(NamedTuple):
    """One emitted token, as returned by ``step()``/``stream()``."""

    request_id: str
    token_id: int
    text: Optional[str]          # incremental detokenized text, if enabled
    finished: bool
    finish_reason: Optional[str]  # "eos" | "length" when finished


def _kv_geometry(model):
    """(num_layers, kv_heads, head_dim) from a CausalLM config."""
    cfg = model.cfg
    kv = getattr(cfg, "num_key_value_heads", None) or \
        cfg.num_attention_heads
    return cfg.num_hidden_layers, kv, cfg.head_dim


def _paged_supported(model) -> bool:
    mdl = getattr(model, "model", None)
    if mdl is None or getattr(model.cfg, "pipeline_stages", 1) != 1:
        return False
    cls = getattr(type(mdl), "decoder_layer_cls", None)
    return cls is not None and getattr(cls, "supports_paged", False)


def _sample(logits, temps, key, seeds, emit):
    """Per-slot greedy (temp==0) or temperature sampling, on device.

    PRNG keys are derived per EMITTED-TOKEN INDEX, never per step:
    slot ``b``'s token at emit index ``emit[b]`` draws from
    ``fold_in(fold_in(key, seeds[b]), emit[b])``, a pure function of
    (engine key, request sample seed, emit index).  A speculative
    engine emitting several tokens in one step therefore draws the
    SAME stream as the non-speculative engine emitting one per step —
    the reproducibility contract that makes spec-on/spec-off
    temperature sampling comparable (docs/SERVING.md "Speculative
    decoding")."""
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1)
    scaled = lg / jnp.maximum(temps, 1e-6)[:, None]

    def draw(seed, idx, row):
        k = jax.random.fold_in(jax.random.fold_in(key, seed), idx)
        return jax.random.categorical(k, row)

    sampled = jax.vmap(draw)(seeds, emit, scaled)
    return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)


def _sample_span(logits, temps, key, seeds, emit):
    """Per-POSITION sampling over a whole ``(B, C, V)`` span — the
    speculative verify step's sampler.  Position ``j`` of slot ``b``
    uses emit index ``emit[b] + j``, so the token drawn at any given
    emit index matches :func:`_sample`'s bit-for-bit (same fold chain),
    whatever mix of spans produced it."""
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1)
    c = lg.shape[1]
    scaled = lg / jnp.maximum(temps, 1e-6)[:, None, None]

    def draw_row(seed, base, rows):
        kb = jax.random.fold_in(key, seed)

        def draw(j, row):
            return jax.random.categorical(
                jax.random.fold_in(kb, base + j), row)

        return jax.vmap(draw)(jnp.arange(c, dtype=jnp.int32), rows)

    sampled = jax.vmap(draw_row)(seeds, emit, scaled)
    return jnp.where(temps[:, None] > 0.0, sampled, greedy).astype(
        jnp.int32)


class Engine:
    """Continuous-batching serving engine (docs/SERVING.md).

    ``model`` is a Llama/GPT-family CausalLM (any config with
    ``supports_paged`` decoder layers and ``pipeline_stages == 1``);
    weights are shared with the dense training/generate() paths via
    ``serving_params``.  ``kv_cache_dtype="int8"`` allocates quantized
    pools (the :func:`quantize_kv` scales, halved KV traffic).

    ``prefill_chunk``: span width C of the unified step (default
    ``min(16, max_seq_len)``) — prompts prefill in ≤C-token chunks
    interleaved with decode, so one compiled
    ``(B, C)`` program serves every batch mix.  ``prefill_token_budget``
    caps the TOTAL prefill tokens scheduled per step (default:
    unbounded, i.e. ``max_batch * prefill_chunk``) — on TPU the ragged
    kernel skips dead pages, so a tighter budget bounds per-step latency
    under bursty admission.

    ``enable_prefix_caching``: hash-based sharing of page-aligned prompt
    prefixes across requests (copy-on-write on shared-page writes, LRU
    eviction of unreferenced cached blocks).  Greedy outputs remain
    token-identical to ``model.generate()`` either way.

    ``detokenize``: optional ``callable(list[int]) -> str``; when given,
    token events and ``on_token`` callbacks carry the incremental text.
    For streaming it is called on a sliding tail window of the output
    (last ``_DETOK_WINDOW`` tokens), so tokenizers whose suffix output
    differs from the suffix of the full output may see a character-level
    seam at window re-anchors (docs/SERVING.md).

    ``keep_finished``: how many finished requests stay queryable via
    :meth:`output_ids` after completion — older ones are evicted so a
    long-running engine's per-request state stays bounded.

    ``max_queue``: bound on the waiting queue; beyond it
    ``add_request`` raises :class:`serving.errors.QueueFull` (default
    unbounded — the FrontDoor applies its own shed policy).
    ``retry``: the :class:`resilience.RetryPolicy` wrapped around
    host-side serving I/O (the preemption swap dispatches); defaults to
    3 attempts with 20 ms base backoff.

    ``weight_quant``: ``"int8"``/``"int4"`` applies the weight-only
    serving transform (``nn.quant.quantize_linears``, IN PLACE on
    ``model``) before the step traces, so decode's projection GEMVs
    stream quantized bytes — on TPU through the fused dequant-in-matmul
    kernels (ops/pallas/int8_matmul.py, int4_matmul.py).  ``page_size``
    and ``prefill_chunk`` also accept ``"auto"``: the values come from
    ``tools/tuned_configs.json`` (per model geometry and backend,
    resolved at construction — never per step).

    ``slo_capture``: an :class:`observability.SLOCapture` (or anything
    with ``on_step()``) consulted after each non-empty step — arms a
    bounded ``jax.profiler`` capture when TTFT p95 breaches its SLO for
    K consecutive windows (docs/OBSERVABILITY.md "Tracing a request").

    ``spec_decode``: self-speculative decoding (docs/SERVING.md
    "Speculative decoding") — a host-side n-gram proposer
    (``serving.spec.NgramProposer``) drafts up to ``draft_depth``
    tokens per decode slot per step and the SAME unified ragged step
    verifies the whole ``[pending, d_1..d_k]`` span like a prefill
    chunk, emitting the accepted prefix plus one bonus token.  Greedy
    outputs stay token-identical to the non-speculative engine;
    temperature slots ride the same program at ``draft_len = 0`` (v1).
    Enabling it widens the compiled span to
    ``max(prefill_chunk, draft_depth + 1)`` — ONE step program per
    engine either way, and the zero-recompile contract is unchanged
    (a slot with no viable draft is just ``draft_len = 0`` data).

    ``mesh``: a serving mesh (``serving.distributed.serving_mesh``)
    makes this engine TENSOR-PARALLEL: parameters land sharded by their
    partition specs, the paged KV pools shard their head axis over the
    mesh's ``mp`` axis (block axis replicated, so the allocator/prefix
    cache/CoW host bookkeeping is untouched), and the one compiled step
    + CoW + swap programs partition under GSPMD — same zero-recompile
    contract, greedy outputs token-identical to the single-chip engine
    (docs/SERVING.md "Sharded serving").

    ``lora``: a :class:`serving.LoRAPool` makes this engine MULTI-LORA
    (docs/SERVING.md "Multi-LoRA"): many fine-tuned adapters resident
    at once as stacked low-rank deltas, each request naming its adapter
    at ``add_request(adapter=...)`` (``FrontDoor`` maps tenants via
    ``TenantPolicy(adapter=)``).  The per-slot adapter index rides
    ``span_arrays`` as DATA into the one compiled step, where the
    grouped BGMV (``incubate.nn.functional.lora_bgmv``) adds
    ``x @ A_i @ B_i`` to every LoRA-targeted projection — mixed
    adapters in one batch, zero recompiles on adapter load/evict
    (buffer writes into the fixed-shape stacks), and base-model
    requests ride slot 0's exact no-op bitwise-unchanged.  Greedy
    outputs under adapter ``k`` are token-identical to a merged-weight
    (``W + B_k A_k``) model.  The LoRA engine pins the UNFUSED
    qkv/MLP projection path (the deltas inject pre-RoPE and around the
    activation, which the fused single-pass kernels cannot expose).

    ``role``: disaggregated serving (docs/SERVING.md "Disaggregated
    serving").  ``"both"`` (default) is the colocated engine above.
    ``"prefill"`` retires every request at prefill-complete — the first
    token is sampled and emitted (TTFT stops on this replica), the KV
    pages swap to host, the slot frees — and parks the state on
    ``handed_off`` for a ``serving.DisaggReplicaSet`` (or any driver)
    to stream to a decode replica.  ``"decode"`` receives transferred
    ``KVHandout``s via :meth:`admit_handout` and resumes decode at
    ``kv_len`` through the restore path; its ``add_request`` still
    accepts fresh prompts (the re-prefill fallback after a hard
    transfer failure).  All three roles run the SAME compiled step —
    role changes which host paths fire, never the program.
    """

    def __init__(self, model, *, max_batch: int = 8,
                 max_seq_len: int = 256, page_size: int = 16,
                 num_blocks: Optional[int] = None,
                 kv_cache_dtype=None,
                 prefill_chunk: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None,
                 enable_prefix_caching: bool = True,
                 detokenize: Optional[Callable] = None, seed: int = 0,
                 keep_finished: int = 1024,
                 max_queue: Optional[int] = None,
                 retry: Optional[RetryPolicy] = None,
                 mesh=None,
                 weight_quant: Optional[str] = None,
                 slo_capture=None,
                 spec_decode: bool = False,
                 draft_depth: int = 4,
                 role: str = "both",
                 lora=None):
        if not _paged_supported(model):
            raise NotImplementedError(
                f"{type(model).__name__} does not support the paged "
                "serving path (needs supports_paged decoder layers and "
                "pipeline_stages == 1)")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got "
                f"{role!r} (docs/SERVING.md \"Disaggregated serving\")")
        n_layers, kv_heads, head_dim = _kv_geometry(model)
        if page_size == "auto" or prefill_chunk == "auto":
            # tuned serving knobs (tools/tuned_configs.json): resolved
            # HERE, before any trace — warmup compiles against the
            # resolved values and steady state never re-reads them (the
            # zero-recompile contract; ops.tuning docstring)
            from ..ops import tuning
            scfg = tuning.tuned_config(
                "serving", tuning.geom_key(
                    h=model.cfg.hidden_size, l=n_layers, kv=kv_heads,
                    hd=head_dim))
            if page_size == "auto":
                page_size = scfg.get("page_size", 16)
            if prefill_chunk == "auto":
                prefill_chunk = scfg.get("prefill_chunk", None)
        if max_batch < 1 or max_seq_len < page_size:
            raise ValueError(
                f"bad geometry: max_batch={max_batch}, "
                f"max_seq_len={max_seq_len}, page_size={page_size}")
        if prefill_chunk is None:
            prefill_chunk = min(16, int(max_seq_len))
        if not 1 <= prefill_chunk <= max_seq_len:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be in "
                f"[1, max_seq_len={max_seq_len}]")
        self.spec = None
        self.draft_depth = 0
        if spec_decode:
            if not 1 <= int(draft_depth) <= max_seq_len - 1:
                raise ValueError(
                    f"draft_depth={draft_depth} must be in "
                    f"[1, max_seq_len-1={max_seq_len - 1}]")
            self.draft_depth = int(draft_depth)
            from .spec import NgramProposer
            self.spec = NgramProposer(self.draft_depth)
            # the verify span [pending, d_1..d_K] must fit the one
            # compiled (B, C) step: widen C once, HERE, before any
            # trace — warmup compiles against the widened span and
            # every draft depth 0..K rides it as span-length DATA
            prefill_chunk = max(int(prefill_chunk), self.draft_depth + 1)
        max_pos = getattr(model.cfg, "max_position_embeddings", None)
        if max_pos is not None and max_seq_len > max_pos:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the model's "
                f"max_position_embeddings={max_pos}")
        if weight_quant is not None and lora is not None:
            # the stacked-delta path targets the model's float 2-D
            # projection weights; quantized layers keep int codes +
            # separate scales, so the pool's geometry check (and the
            # merged-weight identity contract) cannot hold — reject
            # loudly instead of failing with a misleading shape error
            raise ValueError(
                "Engine(lora=...) does not compose with weight_quant "
                "yet — serve LoRA adapters on the float decode path "
                "(docs/SERVING.md \"Multi-LoRA\")")
        if weight_quant is not None:
            # decode weight path (docs/KERNELS.md): swap the model's
            # Linears for weight-only quantized variants IN PLACE (the
            # serving transform, nn.quant) so the decode GEMVs stream
            # int8/int4 — on TPU through the fused dequant-in-matmul
            # kernels.  Done AFTER every constructor validation above (a
            # rejected construction must not corrupt the caller's still-
            # usable model) and before serving_params below, so the
            # quantized buffers ride the compiled step as inputs;
            # model.generate() on the same object sees the same weights,
            # keeping greedy token-identity checkable.
            from ..nn.quant import quantize_linears
            algo = {"int8": "weight_only_int8",
                    "int4": "weight_only_int4"}.get(weight_quant,
                                                    weight_quant)
            quantize_linears(model, algo=algo)
        model.eval()
        self.model = model
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.page_size = int(page_size)
        self.prefill_chunk = int(prefill_chunk)
        # a zero/negative budget would idle every prefilling slot forever
        self.prefill_token_budget = None if prefill_token_budget is None \
            else max(1, int(prefill_token_budget))
        self.max_blocks_per_seq = -(-self.max_seq_len // self.page_size)
        if num_blocks is None:
            # enough for every slot to run a full-length sequence
            num_blocks = self.max_batch * self.max_blocks_per_seq
        dtype = kv_cache_dtype if kv_cache_dtype is not None else \
            getattr(model.cfg, "dtype", "float32")
        self.mesh = mesh
        self.kv = PagedKVCache(n_layers, num_blocks, self.page_size,
                               kv_heads, head_dim, dtype=dtype, mesh=mesh)
        self.prefix_cache = PrefixCache(self.kv.allocator, self.page_size) \
            if enable_prefix_caching else None
        self.scheduler = Scheduler(self.max_batch, self.page_size,
                                   self.max_blocks_per_seq,
                                   self.kv.allocator, self.kv.oob_block,
                                   prefix_cache=self.prefix_cache)
        # preemption/restore machinery: host-RAM page swap plus the
        # retry policy wrapped around serving host I/O (swap dispatches)
        # so a transient (or injected) fault becomes a logged retry, not
        # a dead request
        self.max_queue = None if max_queue is None else int(max_queue)
        self._retry = retry if retry is not None else \
            RetryPolicy(max_attempts=3, backoff_s=0.02)
        self._swap = SwapManager(self.kv, chunk=self.max_blocks_per_seq)
        self.params = serving_params(model)
        if mesh is not None:
            from .distributed import shard_serving_params
            self.params = shard_serving_params(model, self.params, mesh)
        self._detokenize = detokenize
        self._key = jax.random.key(seed)
        # Cross-thread state (the HTTP-handler / engine-loop boundary,
        # serving/server.py): when the engine sits behind a
        # ServingServer, handler threads reach these through
        # FrontDoor.submit while the loop thread mutates them in
        # step().  The guarding lock is ServingServer._lock; methods
        # marked `# requires-lock: _lock` must be entered with it held
        # (single-threaded drivers — tests, benches — satisfy that
        # trivially).  Checked by pdtpu-lint's lock-discipline rule.
        self._states: Dict[str, RequestState] = {}   # guarded_by: _lock
        # a long-running engine must not leak one RequestState (plus its
        # token list) per request served: only the `keep_finished` most
        # recently finished requests stay queryable via output_ids()
        self.keep_finished = int(keep_finished)
        self._finished_order: "collections.deque[str]" = \
            collections.deque()                      # guarded_by: _lock
        # set by run() while draining: finish-time output capture that
        # eviction can't outrun (None outside run(), so step()/stream()
        # users accumulate no unbounded side state)
        self._drain_capture: Optional[Dict[str, List[int]]] = \
            None                                     # guarded_by: _lock
        self._cow_copies = 0
        # lifetime serving-work accounting: seconds this engine spent in
        # its own step phases (dispatch + sync/post-processing — NOT
        # time a replica-set loop spent on its siblings) and tokens it
        # emitted.  tokens_emitted / busy_s is the per-replica rate the
        # DP aggregate-throughput projection sums (tools/decode_bench).
        self.busy_s = 0.0
        self.tokens_emitted = 0
        # SLO-triggered on-chip capture (observability.trace.SLOCapture
        # or anything with on_step()): consulted once per non-empty
        # step_finish — None (the default) costs one falsy check
        self._slo_capture = slo_capture
        # disaggregated serving (docs/SERVING.md "Disaggregated
        # serving"): a role="prefill" engine RETIRES each request at
        # prefill-complete — first token sampled and emitted (TTFT stops
        # here), pages swapped to host, slot freed — parking the state
        # on `handed_off` for a DisaggReplicaSet (or any driver) to
        # stream to a decode replica.  A role="decode" engine's work
        # arrives as transferred KVHandouts via admit_handout(); its
        # add_request path still accepts fresh prompts, which is the
        # re-prefill fallback after a hard transfer failure.  _handoff_ok
        # is an optional veto hook the replica set installs (e.g. "no
        # healthy decode replica right now" → keep decoding locally).
        # batched multi-LoRA (docs/SERVING.md "Multi-LoRA"): the stacked
        # adapter pools ride every step as fixed-shape jit inputs, so
        # the pool may be hot-loaded/evicted between steps (value edits
        # only — the zero-recompile contract extends to adapter churn)
        if lora is not None:
            lora.validate(model)
        self.lora = lora
        self.role = role
        self._handoff_ok: Optional[Callable[[], bool]] = None
        self.handed_off: "collections.deque[RequestState]" = \
            collections.deque()                  # guarded_by: _lock
        self.handoffs = 0            # lifetime prefill-complete handoffs
        self._warmed = False
        # analytic roofline minimums per warmup program (filled by
        # _publish_compiled_obs when the compiled-artifact ledger is
        # active; None keeps the disabled path at one falsy check)
        self._roofline_min_ms: Optional[Dict[str, float]] = None
        # structural dispatch count of the one step program (filled
        # lazily by dispatches_per_step — an abstract trace, no compile)
        self._dispatches_per_step: Optional[int] = None
        self._build_fns()

    # -- compiled paths ----------------------------------------------------

    def _build_fns(self):
        model = self.model
        spec = self.spec is not None

        def _logits_of(params, hidden):
            with _swapped_params(model, params):
                return model.logits(hidden)[:, 0]

        def step_fn(params, caches, tokens, tables, starts, lens, temps,
                    key, seeds, emit, lora_ab, adapters):
            """The ONE serving program: every slot's span (prefill
            chunk, decode token, or decode-plus-draft verify span)
            writes its KV and attends in a single ragged dispatch.
            Non-speculative engines sample one token per slot from the
            last real span position (hosts of mid-prefill slots discard
            it); speculative engines sample EVERY span position — the
            per-position argmax IS the verification (position ``j``'s
            sample is the model's token after consuming draft ``j``),
            so accept/reject needs no second dispatch.  ``lora_ab`` is
            the stacked adapter pytree (None on non-LoRA engines — the
            model path is then byte-for-byte today's) and ``adapters``
            the per-slot stack indices the grouped BGMV gathers by."""
            mp = {k[len("model."):]: v for k, v in params.items()
                  if k.startswith("model.")}
            hidden, caches = functional_call(
                model.model, mp, tokens, caches=caches, seq_lens=lens,
                block_tables=tables, span_starts=starts,
                lora=None if lora_ab is None else (lora_ab, adapters),
                training=False)
            if spec:
                with _swapped_params(model, params):
                    lg = model.logits(hidden)          # (B, C, V)
                return _sample_span(lg, temps, key, seeds, emit), caches
            # the last REAL span token's hidden state, not the padding's
            idx = jnp.clip(lens - 1, 0, tokens.shape[1] - 1)[:, None, None]
            h_last = jnp.take_along_axis(hidden, idx, axis=1)
            lg = _logits_of(params, h_last)
            return _sample(lg, temps, key, seeds, emit), caches

        def cow_fn(caches, src, dst):
            """Copy-on-write page copies src[i] → dst[i] in every layer's
            pools; padded entries carry the OOB sentinel (dropped)."""
            from ..incubate.nn.functional import paged_copy_blocks
            return [paged_copy_blocks(c, src, dst) for c in caches]

        # pools are donated: the engine owns exactly one copy in HBM
        self._step_fn_raw = step_fn   # for dispatches_per_step's trace
        self._step_fn = jax.jit(step_fn, donate_argnums=(1,))
        self._cow_fn = jax.jit(cow_fn, donate_argnums=(0,))

    def _lora_stacks(self):
        """The stacked adapter pytree threaded through every step — the
        pool's cached device arrays (fixed shapes, so a hot load/evict
        between steps is a new VALUE at the same jit entry), or None
        when this engine serves the base model only."""
        return self.lora.device_stacks() if self.lora is not None \
            else None

    def _trace_mesh(self):
        """Mesh-override context for trace-triggering calls: under a
        serving mesh the model's TP sharding constraints
        (``mp_layers.constrain``) must see THIS engine's mesh while the
        step traces — DP replicas each trace under their own submesh, so
        the global fleet state cannot carry it.  No-op single-chip;
        steady-state dispatches hit the jit cache and never re-enter."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from .distributed import trace_mesh
        return trace_mesh(self.mesh)

    def warmup(self) -> "Engine":
        """Compile the unified ragged step, the CoW helper, and the two
        swap programs (preemption gather/scatter) up front.

        Uses all-out-of-range block tables and zero span lengths, so the
        warmup traffic's writes are dropped — no allocator interaction,
        no pool pollution.  After this, serving traffic compiles NOTHING
        — preemption, restore, and fault-isolation churn included (the
        serving-smoke and chaos-serving gates' contract).

        Each compile group runs inside a recompile-sentinel site scope
        (serve.step / serve.cow / serve.swap / serve.lora) so the
        compiled-artifact ledger's rows land with attribution — a pure
        labelling change; the program set and compile count are
        byte-for-byte the pre-ledger warmup's."""
        tel = obs.get_telemetry()
        sent = tel.sentinel if tel is not None else None

        def _site(name):
            # warmup=True: these compiles are the expected one-per-group
            # set — attributed and counted, but never storm candidates
            # (a process may legitimately warm many engines)
            return sent.site(name, warmup=True) if sent is not None \
                else contextlib.nullcontext()

        with span("serve.warmup"), self._trace_mesh():
            b, mb, c = self.max_batch, self.max_blocks_per_seq, \
                self.prefill_chunk
            oob = np.full((b, mb), self.kv.oob_block, np.int32)
            zeros_i = np.zeros((b,), np.int32)
            with _site("serve.step"):
                nxt, caches = self._step_fn(
                    self.params, self.kv.caches,
                    jnp.asarray(np.zeros((b, c), np.int32)),
                    jnp.asarray(oob),
                    jnp.asarray(zeros_i), jnp.asarray(zeros_i),
                    jnp.asarray(np.zeros((b,), np.float32)),
                    self._key, jnp.asarray(zeros_i), jnp.asarray(zeros_i),
                    self._lora_stacks(), jnp.asarray(zeros_i))
                jax.block_until_ready(nxt)
            self.kv.caches = caches
            pad = np.full((b,), self.kv.oob_block, np.int32)
            with _site("serve.cow"):
                caches = self._cow_fn(self.kv.caches, jnp.asarray(pad),
                                      jnp.asarray(pad))
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(caches)[0])
            self.kv.caches = caches
            with _site("serve.swap"):
                self._swap.warmup()
            if self.lora is not None:
                # compile the pool's per-slot scatter programs here so
                # hot-load/evict under churn stays at 0 compiles
                with _site("serve.lora"):
                    self.lora.prime_updates()
        # only AFTER the work: a failed warmup must leave step_begin's
        # auto-warmup safety net armed for mesh engines
        self._warmed = True
        self._publish_compiled_obs()
        return self

    def hbm_stats(self) -> Dict[str, int]:
        """Live HBM accounting: bytes owned by each device-resident
        pool — ``kv_pool_bytes`` (the paged KV pools), ``lora_pool_bytes``
        (stacked adapter pools), ``param_bytes`` (serving weights) —
        plus ``peak_temp_bytes``, the largest XLA scratch allocation any
        compiled program needs while running (from the compiled-artifact
        ledger's memory_analysis; 0 when telemetry is off).  Pure buffer
        arithmetic — safe without telemetry, used by worker exit
        reports."""

        def _nbytes(tree) -> int:
            return sum(int(getattr(leaf, "nbytes", 0) or 0)
                       for leaf in jax.tree_util.tree_leaves(tree))

        stats = {"kv_pool_bytes": int(self.kv.nbytes()),
                 "lora_pool_bytes": _nbytes(self._lora_stacks()),
                 "param_bytes": _nbytes(self.params),
                 "peak_temp_bytes": 0}
        led = _obs_state.LEDGER[0]
        if led is not None:
            stats["peak_temp_bytes"] = max(
                (r.get("temp_bytes", 0) for r in led.snapshot()),
                default=0)
        return stats

    def dispatches_per_step(self) -> int:
        """Structural dispatch count of the ONE serving step: the number
        of top-level equations in the traced step program.  The fused
        entry points (custom_vjp-wrapped — ``fused_rms_rope_qkv``,
        ``fused_swiglu_mlp``, and the whole-layer ``mega_decode_layer``)
        close over their internals and count as ONE equation each,
        mirroring their one-dispatch lowering on TPU; XLA may still fuse
        neighboring elementwise equations off-chip, so this is a
        structural proxy (program shape, not measured kernel launches)
        — which is exactly what makes the mega-vs-on-vs-off A/B honest
        on CPU.  Pure abstract trace: nothing compiles, the recompile
        sentinel never fires.  Cached after the first call."""
        if self._dispatches_per_step is None:
            b, mb, c = self.max_batch, self.max_blocks_per_seq, \
                self.prefill_chunk
            oob = jnp.asarray(np.full((b, mb), self.kv.oob_block,
                                      np.int32))
            zi = jnp.asarray(np.zeros((b,), np.int32))
            with self._trace_mesh():
                jaxpr = jax.make_jaxpr(self._step_fn_raw)(
                    self.params, self.kv.caches,
                    jnp.asarray(np.zeros((b, c), np.int32)), oob,
                    zi, zi, jnp.asarray(np.zeros((b,), np.float32)),
                    self._key, zi, zi, self._lora_stacks(), zi)
            self._dispatches_per_step = len(jaxpr.jaxpr.eqns)
        return self._dispatches_per_step

    def _publish_compiled_obs(self) -> None:
        """Post-warmup: the ``serve.hbm.*`` gauge block and per-program
        analytic roofline minimums (``serve.roofline.<prog>.min_ms``)
        from the compiled-artifact ledger.  Cold path (runs once per
        warmup); with telemetry off it is exactly two falsy checks."""
        reg = obs.get_registry()
        led = _obs_state.LEDGER[0]
        if reg is None and led is None:
            return
        hbm = self.hbm_stats()
        if led is not None:
            # snapshot for exit reports / postmortems: the memory
            # picture survives even after the engine is gone
            led.set_hbm(hbm)
            mins: Dict[str, float] = {}
            pairs = [("step", "serve.step"), ("cow", "serve.cow"),
                     ("swap", "serve.swap"), ("lora", "serve.lora")]
            if getattr(getattr(self.model, "cfg", None), "fused_ops",
                       None) == "mega":
                # the megakernel step's roofline row, tagged so A/B
                # dashboards overlay mega-on vs mega-off engines
                # without aliasing the plain step row
                pairs.append(("step.mega", "serve.step"))
            for key, site in pairs:
                m = led.min_ms_for(site)
                if m:
                    mins[key] = m
            self._roofline_min_ms = mins
        if reg is not None:
            for k, v in hbm.items():
                reg.gauge(f"serve.hbm.{k}").set(v)
            reg.gauge("serve.dispatches_per_step").set(
                self.dispatches_per_step())
            for key, m in (self._roofline_min_ms or {}).items():
                reg.gauge(f"serve.roofline.{key}.min_ms").set(round(m, 6))

    # -- request lifecycle -------------------------------------------------

    # requires-lock: _lock — touches _states (see __init__)
    def add_request(self, prompt_ids, max_new_tokens: int = 16,
                    temperature: float = 0.0,
                    eos_token_id: Optional[int] = None,
                    on_token: Optional[Callable] = None,
                    request_id: Optional[str] = None,
                    tenant: Optional[str] = None,
                    adapter: Optional[str] = None,
                    _page_keys: Optional[List[bytes]] = None) -> str:
        """Queue one request; returns its id.  The request joins the
        running batch at the next ``step()`` with a free slot and enough
        free blocks for its budget (prompt + max_new_tokens, minus any
        prefix-cache hit).  ``adapter`` names a LoRA adapter resident in
        this engine's pool (``Engine(lora=...)``); the request then
        decodes through ``W + B_k A_k`` while sharing the batch, the
        cache and the one compiled step with every other tenant.

        Rejections are typed (``serving.errors``, all ``ValueError``
        subclasses): :class:`QueueFull` when ``max_queue`` is set and
        the waiting queue is at capacity (transient — retry later),
        :class:`BudgetUnsatisfiable` when the request can never fit this
        engine's geometry, :class:`UnknownAdapter` for an adapter this
        engine has not loaded (validated HERE, at admission — a bad
        tenant→model mapping must never strand a half-admitted
        request), plain :class:`AdmissionError` for a duplicate
        ``request_id``."""
        req = Request(prompt_ids=prompt_ids,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_token_id=eos_token_id, on_token=on_token,
                      request_id=request_id, tenant=tenant,
                      adapter=adapter)
        if adapter is not None:
            if self.lora is None:
                raise UnknownAdapter(
                    f"request names adapter {adapter!r} but this engine "
                    "has no LoRA pool (Engine(lora=serving.LoRAPool(...)))")
            req.adapter_slot = self.lora.slot_of(adapter)
            # refcount from the moment the slot resolves (released below
            # on any rejection): an evict racing the admission checks
            # must hit typed AdapterInUse, never strand a half-admitted
            # request on a vanished slot
            self.lora.acquire(adapter, req.request_id)
        try:
            self._admission_checks(req, _page_keys=_page_keys)
        except Exception:
            if adapter is not None:
                self.lora.release(adapter, req.request_id)
            raise
        tr = _obs_state.TRACE[0]
        if tr is not None:
            # get-or-create: a door-submitted request already began its
            # trace at door submit (queue time there is queue time here)
            req.trace_id = tr.begin(
                req.request_id, tenant=req.tenant, trace_id=req.trace_id,
                prompt_len=int(req.prompt_ids.size),
                max_new=req.max_new_tokens)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.requests").inc()
            reg.gauge("serve.queue_depth").set(self.scheduler.queue_depth())
            if adapter is not None:
                reg.counter(
                    f"serve.lora.adapter[{adapter}].requests").inc()
        return req.request_id

    # add_request's validate+submit body, split out so the adapter
    # refcount above wraps EVERY rejection path
    # requires-lock: _lock — touches _states
    def _admission_checks(self, req: Request,
                          _page_keys: Optional[List[bytes]] = None):
        if req.request_id in self._states:
            # a silent overwrite would orphan the first request's slot /
            # blocks bookkeeping and lose its output
            raise AdmissionError(
                f"request_id {req.request_id!r} is already in use by a "
                "live or retained request")
        if self.max_queue is not None \
                and self.scheduler.queue_depth() >= self.max_queue:
            raise QueueFull(
                f"waiting queue is at max_queue={self.max_queue} — "
                "retry later (or put a serving.FrontDoor in front for "
                "retry-after answers instead of exceptions)")
        p = int(req.prompt_ids.size)
        if p + req.max_new_tokens > self.max_seq_len:
            raise BudgetUnsatisfiable(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds max_seq_len={self.max_seq_len}")
        need = self.scheduler.blocks_for(p + req.max_new_tokens)
        if need > self.kv.num_blocks:
            # an unsatisfiable reservation would sit at the queue head
            # forever and make run()/stream() spin — reject it up front
            raise BudgetUnsatisfiable(
                f"request needs {need} KV blocks (prompt {p} + "
                f"max_new_tokens {req.max_new_tokens} @ page "
                f"{self.page_size}) but the pool has only "
                f"{self.kv.num_blocks} — raise num_blocks or lower the "
                "budget")
        # _page_keys: prompt page digests a router already computed for
        # its affinity probe — forwarded so submit() does not re-run the
        # O(prompt) blake2b chain (serving/distributed.py)
        st = self.scheduler.submit(req, page_keys=_page_keys)
        self._states[req.request_id] = st

    # requires-lock: _lock
    def output_ids(self, request_id: str) -> List[int]:
        return list(self._states[request_id].output_ids)

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    @property
    def kv_blocks_used(self) -> int:
        return self.kv.allocator.used_blocks

    def prefix_stats(self) -> Dict[str, float]:
        """Prefix-cache counters (hits/misses/hit_rate/registered_pages/
        evictions) plus the current CoW copy count — zeros when prefix
        caching is disabled."""
        s = self.prefix_cache.stats() if self.prefix_cache is not None \
            else {"hits": 0, "misses": 0, "hit_rate": 0.0,
                  "registered_pages": 0, "evictions": 0}
        s["cow_copies"] = self._cow_copies
        return s

    # -- preemption / restore / fault isolation ----------------------------

    # requires-lock: _lock — reads _states
    def preempt(self, request_id: str, *, requeue_head: bool = False,
                reason: str = "preempted") -> bool:
        """Swap a RUNNING request's KV pages to host RAM, free its
        blocks and slot, and requeue it for transparent restoration —
        the front door's alternative to rejecting new work when the
        pool is tight (docs/SERVING.md "Front door").

        Returns False when the request is not currently in a slot
        (waiting, already preempted, finished, or unknown).  The
        restored request resumes token-identical under greedy decoding:
        the swap round-trips the exact page bytes (int8 scales
        included), and shared prefix pages are only COPIED — never
        pulled out from under the other slots referencing them."""
        st = self._states.get(request_id)
        if st is None or st.finished or st.slot is None:
            return False
        self._preempt_state(st, head=requeue_head, reason=reason)
        return True

    def _preempt_state(self, st: RequestState, head: bool,
                       reason: str) -> None:
        pages = -(-st.kv_len // self.page_size)
        host = None
        if pages:
            ids = [int(b) for b in st.table[:pages]]
            host = self._retry.run(self._swap.swap_out, ids,
                                   site="serve.swap")
        self.scheduler.release_slot(st)
        # everything comes back private at restore: for the shared-pages
        # gauge the borrowed pages count as privatized from here on
        st.num_cowed = st.num_shared
        st.swapped = (pages, host)
        st.preempts += 1
        self.scheduler.requeue(st, head=head)
        tr = _obs_state.TRACE[0]
        if tr is not None:
            tr.transition(st.request.request_id, "queue", event="preempt",
                          reason=reason, pages=pages, kv_len=st.kv_len)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.preemptions").inc()
            if pages:
                reg.counter("serve.swapped_pages").inc(pages)
        obs.emit_event("serve_preempt", id=st.request.request_id,
                       tenant=st.request.tenant, pages=pages,
                       kv_len=st.kv_len, reason=reason,
                       preempts=st.preempts)

    def _restore(self, st: RequestState) -> None:
        """Scatter a freshly re-admitted request's host payload into its
        new (all-private) blocks; prefill/decode resumes at kv_len."""
        pages, host = st.swapped
        if pages:
            ids = [int(b) for b in st.table[:pages]]
            self._retry.run(self._swap.swap_in, ids, host,
                            site="serve.swap")
        st.swapped = None
        tr = _obs_state.TRACE[0]
        if tr is not None:
            tr.point(st.request.request_id, "restore", pages=pages,
                     kv_len=st.kv_len)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.restores").inc()
        obs.emit_event("serve_restore", id=st.request.request_id,
                       tenant=st.request.tenant, pages=pages,
                       kv_len=st.kv_len)

    def _isolate(self, st: RequestState, exc: Exception) -> None:
        """Confine a failing request to ITS slot: the compiled step and
        the batch's other requests survive; the victim is preempted to
        host and transparently re-admitted (queue head — it was
        mid-flight).  Greedy outputs stay token-identical because the
        caller rewound the host bookkeeping to the pre-span snapshot
        and re-running a span is idempotent (same values, same
        positions)."""
        rid = st.request.request_id
        warnings.warn(
            f"request {rid!r} failed host-side and was isolated "
            f"(preempt + re-admit; {type(exc).__name__}: {exc})",
            RuntimeWarning, stacklevel=3)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.isolated_failures").inc()
        obs.emit_event("serve_isolated_failure", id=rid,
                       tenant=st.request.tenant,
                       exc=type(exc).__name__, message=str(exc)[:200])
        tr = _obs_state.TRACE[0]
        if tr is not None:
            tr.point(rid, "isolated", exc=type(exc).__name__)
        self._preempt_state(st, head=True, reason="isolated_failure")

    # requires-lock: _lock — drains scheduler.waiting
    def _admit_all(self) -> None:
        """Admission loop with the ``serve.admit`` fault site: an
        injected/host fault here leaves the queue intact (nothing has
        been allocated yet) and admission simply resumes next step."""
        fi = _rs_state.FAULTS[0]
        while self.scheduler.waiting:
            if fi is not None:
                try:
                    fi("serve.admit")
                except Exception as e:  # noqa: BLE001
                    reg = obs.get_registry()
                    if reg is not None:
                        reg.counter("serve.isolated_failures").inc()
                    obs.emit_event(
                        "serve_isolated_failure", id=None, tenant=None,
                        exc=type(e).__name__, message=str(e)[:200],
                        site="serve.admit")
                    break
            st = self.scheduler.admit_next()
            if st is None:
                break
            tr = _obs_state.TRACE[0]
            if tr is not None:
                # queue→slot transition: closes the queue-wait segment
                # (first admission AND each post-preempt re-admission)
                tr.transition(
                    st.request.request_id,
                    "prefill" if st.prefilling else "decode",
                    event="admit", slot=st.slot, kv_len=st.kv_len,
                    cached_tokens=st.cached_tokens)
            if st.swapped is not None:
                self._restore(st)

    # -- the loop ----------------------------------------------------------

    def _run_cow(self, plan):
        """Copy-on-write: any span about to write into a borrowed
        (shared) page gets a private copy first — the reserved spare
        block takes the page's content via one fixed-shape device copy,
        the table is repointed, and the shared reference is dropped.
        Returns the plan minus any request isolated by a ``serve.cow``
        fault (fired BEFORE that request's tables are touched, so
        isolation sees consistent state)."""
        fi = _rs_state.FAULTS[0]
        copies = []
        dropped = []
        for i, st, n, is_prefill in plan:
            if not st.borrowed:
                continue
            first = st.kv_len // self.page_size
            last = (st.kv_len + n - 1) // self.page_size
            pgs = [pg for pg in range(first, last + 1) if pg in st.borrowed]
            if not pgs:
                continue
            if fi is not None:
                try:
                    fi("serve.cow")
                except Exception as e:  # noqa: BLE001
                    # nothing mutated for this request yet this step:
                    # plain isolation, and its span leaves the plan
                    self._isolate(st, e)
                    dropped.append(i)
                    continue
            for pg in pgs:
                src = int(st.table[pg])
                dst = st.cow_spare.pop(pg)
                st.table[pg] = dst
                st.borrowed.discard(pg)
                st.num_cowed += 1
                st.blocks.remove(src)
                self.kv.allocator.free([src])   # drop OUR shared ref
                copies.append((src, dst))
        if dropped:
            plan = [it for it in plan if it[0] not in dropped]
        if not copies:
            return plan
        k = self.max_batch
        for lo in range(0, len(copies), k):
            batch = copies[lo:lo + k]
            src = np.full((k,), self.kv.oob_block, np.int32)
            dst = np.full((k,), self.kv.oob_block, np.int32)
            for j, (s_, d_) in enumerate(batch):
                src[j], dst[j] = s_, d_
            self.kv.caches = self._cow_fn(self.kv.caches,
                                          jnp.asarray(src),
                                          jnp.asarray(dst))
        self._cow_copies += len(copies)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.cow_copies").inc(len(copies))
        return plan

    def _register_prefix(self, st: RequestState) -> None:
        """Index this request's freshly-written full prompt pages so
        later requests with the same prefix hit them.  Pages borrowed
        from the cache are already indexed (register no-ops on a live
        key); first writer wins when two identical prompts prefill
        concurrently."""
        if self.prefix_cache is None:
            return
        for pg, key in enumerate(st.page_keys):
            self.prefix_cache.register(key, int(st.table[pg]))

    # -- disaggregated roles (docs/SERVING.md "Disaggregated serving") -----

    def _prepare_handoff(self, st: RequestState, tok: int):
        """Stage a prefill-complete request's handoff to a decode
        replica: when this engine is ``role="prefill"``, the request
        will keep decoding, and the handoff hook (if any) approves,
        gather its KV pages to host and return ``(pages, payload)`` for
        :meth:`_commit_handoff`.  Returns None to decode locally — a
        finishing request, a vetoed handoff (no decode capacity), or a
        hard swap failure (nothing has mutated yet, so degrading to
        local decode is free and the request is never lost)."""
        if self.role != "prefill":
            return None
        req = st.request
        if req.eos_token_id is not None and tok == req.eos_token_id:
            return None              # finishes right here: plain retire
        if len(st.output_ids) + 1 >= req.max_new_tokens:
            return None
        ok = self._handoff_ok
        if ok is not None and not ok():
            return None              # the set vetoed: decode locally
        pages = -(-st.kv_len // self.page_size)
        try:
            ids = [int(b) for b in st.table[:pages]]
            return pages, self._retry.run(self._swap.swap_out, ids,
                                          site="serve.swap")
        except Exception as e:  # noqa: BLE001 — hard swap failure
            warnings.warn(
                f"handoff swap-out for request {req.request_id!r} "
                f"failed ({type(e).__name__}: {e}); decoding locally",
                RuntimeWarning, stacklevel=3)
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("serve.handoff_failures").inc()
            return None

    # requires-lock: _lock — parks onto handed_off
    def _commit_handoff(self, st: RequestState, handoff) -> None:
        """Retire a prefill-complete request from THIS engine: free its
        slot and blocks (the prompt pages stay indexed in the prefix
        cache as evictable capacity — the prefill tier keeps its hit
        rate), park the swapped state on ``handed_off`` for the replica
        set to stream to a decode replica.  The state carries the
        emitted first token as ``pending_token``, so the decode side
        resumes exactly where a colocated engine would."""
        pages, host = handoff
        self.scheduler.release_slot(st)
        # everything comes back private at restore: for the shared-pages
        # gauge the borrowed pages count as privatized from here on
        st.num_cowed = st.num_shared
        st.swapped = (pages, host)
        st.handoffs += 1
        self.handoffs += 1
        if self.lora is not None and st.request.adapter is not None:
            # the request leaves THIS engine; the decode tier's
            # admit_handout re-acquires on its pool (same object
            # in-process — the id-keyed refcount makes that a no-op
            # overlap, not a double count)
            self.lora.release(st.request.adapter, st.request.request_id)
        self.handed_off.append(st)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.handoffs").inc()
            if pages:
                reg.counter("serve.swapped_pages").inc(pages)
        obs.emit_event("serve_handoff", id=st.request.request_id,
                       tenant=st.request.tenant, pages=pages,
                       kv_len=st.kv_len, handoffs=st.handoffs)

    # requires-lock: _lock — touches _states
    def admit_handout(self, handout, *, on_token: Optional[Callable] = None,
                      head: bool = False) -> str:
        """Queue a transferred :class:`~paddle_tpu.serving.KVHandout`
        (bytes straight off a transport, or an already-decoded one) for
        admission — the disaggregated decode replica's intake path.  The
        next step with a free slot and enough blocks restores the pages
        through the compiled swap scatter and decode resumes at
        ``kv_len``; no token is ever re-prefilled.  ``on_token``
        re-attaches a host-local streaming callback (callbacks cannot
        ride the wire format; in-process drivers pass the original)."""
        from .disagg import KVHandout
        if isinstance(handout, (bytes, bytearray, memoryview)):
            handout = KVHandout.from_bytes(bytes(handout))
        st = handout.to_state(on_token=on_token)
        req = st.request
        rid = req.request_id
        if rid in self._states:
            raise AdmissionError(
                f"request_id {rid!r} is already in use by a live or "
                "retained request")
        if req.adapter is not None:
            # the adapter NAME is the wire identity; the slot index is
            # engine-local and re-resolves against THIS engine's pool
            # (typed UnknownAdapter before any state lands — disagg
            # tiers must load the same adapters)
            if self.lora is None:
                raise UnknownAdapter(
                    f"handout {rid!r} names adapter {req.adapter!r} but "
                    "this engine has no LoRA pool")
            req.adapter_slot = self.lora.slot_of(req.adapter)
        total = int(req.prompt_ids.size) + req.max_new_tokens
        if total > self.max_seq_len or \
                self.scheduler.blocks_for(total) > self.kv.num_blocks:
            raise BudgetUnsatisfiable(
                f"handout {rid!r} needs {total} positions / "
                f"{self.scheduler.blocks_for(total)} KV blocks but this "
                f"engine caps at max_seq_len={self.max_seq_len}, "
                f"{self.kv.num_blocks} blocks — disaggregated roles "
                "must share geometry")
        if st.swapped is not None:
            _pages, host = st.swapped
            ok = len(host) == len(self.kv.caches) and all(
                len(hl) == len(cl) and all(
                    tuple(h.shape[1:]) == tuple(c.shape[1:])
                    and np.dtype(h.dtype) == np.dtype(c.dtype)
                    for h, c in zip(hl, cl))
                for hl, cl in zip(host, self.kv.caches))
            if not ok:
                # a mismatched payload would retrace the swap scatter
                # (breaking the zero-recompile contract) or silently
                # corrupt the restored KV — reject before any state lands
                raise ValueError(
                    "handout payload geometry does not match this "
                    "engine's paged pools (page_size / kv heads / "
                    "head_dim / cache dtype must agree across roles)")
        self._states[rid] = st
        self.scheduler.requeue(st, head=head)
        if self.lora is not None and req.adapter is not None:
            # request-id keyed: re-acquire after a shared-pool handoff
            # is idempotent, a distinct-pool decode tier counts its own
            self.lora.acquire(req.adapter, rid)
        tr = _obs_state.TRACE[0]
        if tr is not None:
            # get-or-create keyed by request id: in-process, the trace
            # begun at the door/prefill side just continues; on a
            # separate decode host a fresh timeline begins under the
            # trace id carried by the handout
            req.trace_id = tr.begin(rid, tenant=req.tenant,
                                    trace_id=req.trace_id,
                                    prompt_len=int(req.prompt_ids.size),
                                    max_new=req.max_new_tokens)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("serve.handouts_admitted").inc()
            reg.gauge("serve.queue_depth").set(self.scheduler.queue_depth())
        return rid

    # requires-lock: _lock — retires into _states/_finished_order/_drain_capture
    def _emit(self, st: RequestState, token: int,
              events: List[TokenEvent]):
        req = st.request
        st.output_ids.append(token)
        text = None
        if self._detokenize is not None:
            # linear-cost streaming: detokenize only a bounded tail
            # window, emit its growth, and re-anchor at half-window so
            # per-token work never scales with the full output length
            w = st.detok_offset
            full = self._detokenize(list(st.output_ids[w:]))
            text = full[st.text_len:]
            st.text_len = len(full)
            if len(st.output_ids) - w >= _DETOK_WINDOW:
                st.detok_offset = len(st.output_ids) - _DETOK_WINDOW // 2
                st.text_len = len(self._detokenize(
                    list(st.output_ids[st.detok_offset:])))
        done_eos = (req.eos_token_id is not None
                    and token == req.eos_token_id)
        done_len = len(st.output_ids) >= req.max_new_tokens
        if done_eos or done_len:
            self.scheduler.finish(st, "eos" if done_eos else "length")
            if self.lora is not None and req.adapter is not None:
                # the adapter's slot becomes evictable once its last
                # live reader retires
                self.lora.release(req.adapter, req.request_id)
            if self.spec is not None:
                # bounded proposer retention: the n-gram index dies
                # with the request (it rebuilds lazily if the id is
                # ever reused)
                self.spec.drop(req.request_id)
            tr = _obs_state.TRACE[0]
            if tr is not None:
                spec_kw = {} if self.spec is None else {
                    "spec_proposed": st.spec_proposed,
                    "spec_accepted": st.spec_accepted}
                tr.retire(req.request_id, reason=st.finish_reason,
                          tokens=len(st.output_ids), **spec_kw)
            if self._drain_capture is not None:
                # BEFORE the eviction below: when more requests than
                # keep_finished retire in one step, the state may be
                # gone by the time run() sees the events
                self._drain_capture[req.request_id] = list(st.output_ids)
                st.drained = True
            self._finished_order.append(req.request_id)
            while len(self._finished_order) > self.keep_finished:
                self._states.pop(self._finished_order.popleft(), None)
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("serve.finished").inc()
            obs.emit_event(
                "serve_finish", id=req.request_id,
                reason=st.finish_reason, tokens=len(st.output_ids),
                ms=round((time.perf_counter() - st.submit_t) * 1e3, 3))
        else:
            st.pending_token = token
        events.append(TokenEvent(req.request_id, token, text, st.finished,
                                 st.finish_reason))
        if req.on_token is not None:
            try:
                req.on_token(req.request_id, token, text)
            except Exception:
                # a raising callback must not tear down the whole step:
                # the batch's other requests already produced events this
                # step and their consumers would silently lose them
                warnings.warn(
                    f"on_token callback for request "
                    f"{req.request_id!r} raised; continuing "
                    f"({traceback.format_exc(limit=3).strip()})",
                    RuntimeWarning, stacklevel=2)

    def _propose_drafts(self) -> None:
        """Attach this step's n-gram draft to every eligible decode
        slot (``serving/spec.py``).  Drafting is BEST-EFFORT: a propose
        failure — including an injected ``serve.spec`` fault — degrades
        THAT slot to ``draft_len = 0`` (a plain decode step through the
        same compiled program); it never isolates the request or tears
        into the step.  The cap keeps speculative KV inside the pages
        the request reserved at admission and accepted tokens inside
        its remaining output budget — rollback can then always be pure
        kv_len bookkeeping."""
        fi = _rs_state.FAULTS[0]
        for _i, st in self.scheduler.active():
            st.draft = []
            if st.prefilling or st.request.temperature > 0.0:
                continue             # v1: greedy slots only
            cap = min(self.draft_depth,
                      st.total_len - (st.kv_len + 1),
                      st.request.max_new_tokens - len(st.output_ids) - 1)
            if cap < 1:
                continue
            try:
                if fi is not None:
                    fi("serve.spec")
                st.draft = self.spec.propose(st, cap)
            except Exception as e:  # noqa: BLE001
                self.spec.errors += 1
                reg = obs.get_registry()
                if reg is not None:
                    reg.counter("serve.spec.draft_errors").inc()
                obs.emit_event("serve_spec_error",
                               id=st.request.request_id,
                               exc=type(e).__name__,
                               message=str(e)[:200])
                st.draft = []

    def spec_stats(self) -> Dict[str, float]:
        """Speculative-decoding counters (proposed/accepted/
        accept_rate/verifies/draft_hits/draft_misses/errors/
        tracked_requests) — zeros when ``spec_decode`` is off."""
        if self.spec is None:
            return {"proposed": 0, "accepted": 0, "accept_rate": 0.0,
                    "verifies": 0, "draft_hits": 0, "draft_misses": 0,
                    "errors": 0, "tracked_requests": 0}
        return self.spec.stats()

    def lora_stats(self) -> Dict[str, float]:
        """Multi-LoRA pool counters (active_adapters/max_adapters/rank/
        loads/evictions/live_refs) — zeros when no pool is attached."""
        if self.lora is None:
            return {"active_adapters": 0, "max_adapters": 0, "rank": 0,
                    "loads": 0, "evictions": 0, "live_refs": 0}
        return self.lora.stats()

    def step_begin(self):
        """Admit + plan + CoW + DISPATCH the compiled step without
        waiting for the device; returns the opaque pending handle
        :meth:`step_finish` consumes.  The two-phase split is what lets
        a DP replica set keep every replica's device busy: dispatch all
        replicas back-to-back, then finish them in order, so replica
        ``j``'s compute overlaps replica ``i``'s host bookkeeping
        (serving/distributed.py)."""
        if self.mesh is not None and not self._warmed:
            # a mesh engine must never trace its programs outside the
            # trace-mesh context (the TP constraints would resolve
            # against global fleet state, or nothing) — warm up now
            self.warmup()
        t0 = time.perf_counter()
        with span("serve.step", emit=False):
            self._admit_all()
            if self.spec is not None:
                self._propose_drafts()
            plan = self.scheduler.plan_spans(self.prefill_chunk,
                                             self.prefill_token_budget)
            if plan:
                plan = self._run_cow(plan)
            live_tokens = sum(n for _, _, n, _ in plan)
            nxt = None
            if plan:
                (tokens, tables, starts, lens, temps, seeds, emit,
                 adapters) = self.scheduler.span_arrays(
                    plan, self.prefill_chunk,
                    spec_emit=self.spec is not None)
                # device_put of ready numpy arrays only: jnp.asarray of
                # a Python list/scalar traces a tiny program whose
                # one-off compile would break the zero-compiles-after-
                # warmup contract — draft length reaches the step ONLY
                # inside these traced arrays (span lens/tokens), never
                # as a per-step Python scalar (pdtpu-lint R4f).  The
                # same rule covers adapter ids: per-slot DATA in the
                # adapters array, never a static argument.
                nxt, caches = self._step_fn(
                    self.params, self.kv.caches, jnp.asarray(tokens),
                    jnp.asarray(tables), jnp.asarray(starts),
                    jnp.asarray(lens), jnp.asarray(temps), self._key,
                    jnp.asarray(seeds), jnp.asarray(emit),
                    self._lora_stacks(), jnp.asarray(adapters))
                self.kv.caches = caches
        # busy accounting covers THIS engine's own engagement only
        # (begin and finish timed separately): under a replica set the
        # phases interleave across engines, so begin-to-finish wall
        # clock would charge every engine for its siblings' slices.
        # The same own-time sum feeds serve.step_ms / serve.tok_s in
        # step_finish and the DP throughput projection (decode_bench).
        begin_s = time.perf_counter() - t0
        self.busy_s += begin_s
        return plan, nxt, live_tokens, begin_s

    # requires-lock: _lock — reads _states (per-adapter token counters)
    def step_finish(self, pending) -> List[TokenEvent]:
        """Wait for a :meth:`step_begin` dispatch and run its host
        post-processing: sample consumption, retirement, events,
        per-request fault isolation, telemetry.  ``step_begin`` and
        ``step_finish`` must alternate on one engine (the replica set's
        loop does); :meth:`step` composes them for everyone else."""
        plan, nxt, live_tokens, begin_s = pending
        tf = time.perf_counter()
        events: List[TokenEvent] = []
        # own span so a crash in device sync / post-processing still
        # lands inside a serve.step.* breadcrumb pair on the flight
        # ring (the serve.step span closed with step_begin's dispatch)
        with span("serve.step.finish", emit=False):
            self._finish_events(plan, nxt, events)
        n_tok = len(events)
        now = time.perf_counter()
        # this engine's own step time: begin + finish phases, excluding
        # any sibling-replica slices interleaved between them
        dt = begin_s + (now - tf)
        self.busy_s += now - tf
        self.tokens_emitted += n_tok
        reg = obs.get_registry()
        if reg is not None and plan:
            reg.counter("serve.tokens").inc(n_tok)
            if self.lora is not None:
                # per-adapter token accounting AFTER isolation filtered
                # the events (a rewound span's tokens re-emit after
                # restore and must not count twice); aggregated first so
                # the registry sees one inc per adapter, not per token
                per_ad: Dict[str, int] = {}
                for ev in events:
                    est = self._states.get(ev.request_id)
                    ad = est.request.adapter if est is not None else None
                    if ad is not None:
                        per_ad[ad] = per_ad.get(ad, 0) + 1
                for ad, n in per_ad.items():
                    reg.counter(
                        f"serve.lora.adapter[{ad}].tokens").inc(n)
            reg.gauge("serve.tok_s").set(round(n_tok / max(dt, 1e-9), 1))
            reg.gauge("serve.queue_depth").set(self.scheduler.queue_depth())
            reg.gauge("serve.kv_blocks_used").set(
                self.kv.allocator.used_blocks)
            reg.gauge("serve.active_requests").set(
                len(self.scheduler.active()))
            reg.histogram("serve.step_ms").observe(dt * 1e3)
            # how full the ragged dispatch ran: real span tokens over the
            # (B, C) capacity — low occupancy means idle lanes, not bugs
            reg.histogram("serve.ragged_occupancy").observe(
                live_tokens / (self.max_batch * self.prefill_chunk))
            reg.gauge("serve.cached_blocks").set(
                self.kv.allocator.cached_blocks)
            # pages still physically shared: admission hits minus the
            # ones CoW has since privatized
            reg.gauge("serve.shared_blocks").set(
                sum(s.num_shared - s.num_cowed
                    for _, s in self.scheduler.active()))
            # roofline attribution: measured step wall vs the analytic
            # minimum of the ONE compiled step program (constant per
            # warmup — serve.roofline.step.min_ms).  frac is limit over
            # measured (1.0 = running at the hardware roofline); the
            # step is classed prefill or decode by which token kind
            # dominated its span plan, so the two regimes' distance
            # from the limit is scrapeable separately.
            rf = self._roofline_min_ms
            if rf is not None:
                m = rf.get("step")
                if m:
                    frac = round(m / max(dt * 1e3, 1e-9), 4)
                    n_pref = sum(n for _, _, n, p in plan if p)
                    cls = "prefill" if 2 * n_pref >= live_tokens \
                        else "decode"
                    reg.gauge("serve.roofline.step.frac").set(frac)
                    reg.gauge(f"serve.roofline.{cls}.frac").set(frac)
        if plan:
            obs.emit_event("serve_step", ms=round(dt * 1e3, 3),
                           tokens=n_tok, span_tokens=live_tokens,
                           active=len(self.scheduler.active()),
                           queue=self.scheduler.queue_depth(),
                           kv_blocks_used=self.kv.allocator.used_blocks)
            cap = self._slo_capture
            if cap is not None:
                # SLO-triggered capture bookkeeping: host-side counters
                # only, until a breach arms the bounded profiler window
                cap.on_step()
        return events

    def _finish_events(self, plan, nxt,
                       events: List[TokenEvent]) -> None:
        if plan:
            # np.asarray is the device sync: JAX dispatch is async,
            # so the TTFT clock below must stop AFTER the first
            # token materializes, or it reports queueing overhead
            nxt = np.asarray(nxt)
            fi = _rs_state.FAULTS[0]
            tr = _obs_state.TRACE[0]
            for i, st, n, is_prefill in plan:
                # pre-span snapshot: isolation rewinds to here, and
                # re-running the span after restore is idempotent
                # (the dispatch above already wrote this span's KV;
                # the rewound re-run rewrites identical bytes — a
                # speculative span's rejected tail is re-proposed from
                # the same context, and kv_len only ever covered the
                # accepted prefix)
                snap = (st.kv_len, st.pending_token,
                        len(st.output_ids), st.text_len,
                        st.detok_offset, st.spec_proposed,
                        st.spec_accepted)
                try:
                    if fi is not None:
                        fi("serve.prefill" if is_prefill
                           else "serve.step")
                    if not is_prefill:
                        # decode: plain single token, or the
                        # speculative verify span (mid-verify faults
                        # fired above land in the rollback below)
                        self._consume_decode(st, i, n, nxt, events)
                        continue
                    st.kv_len += n
                    if tr is not None:
                        tr.point(st.request.request_id, "prefill_chunk",
                                 tokens=n, kv_len=st.kv_len)
                    if st.prefilling:
                        continue    # mid-prefill: sample discarded
                    # prompt complete: this sample is the request's
                    # first token — TTFT stops here.  first_token_t
                    # survives a hard replica-failure reset (the
                    # request re-prefills from scratch), so the
                    # re-completion must not re-emit serve_request /
                    # re-observe TTFT for the same request
                    # (serving/distributed.py).  The speculative
                    # program samples every span position; the prompt's
                    # last position carries the first token.
                    tok = int(nxt[i]) if nxt.ndim == 1 \
                        else int(nxt[i, n - 1])
                    self._register_prefix(st)
                    # disaggregated prefill role: stage the handoff
                    # (swap the pages to host) BEFORE any state
                    # mutates — a failed swap degrades cleanly to
                    # local decode, and the trace phase below can
                    # honestly say which way the request went
                    handoff = self._prepare_handoff(st, tok)
                    if tr is not None:
                        # prefill→decode transition (closes the
                        # prefill segment) — or prefill→xfer when this
                        # prefill replica hands the request off to a
                        # decode replica.  A re-completion after a
                        # hard replica reset accumulates under its
                        # own event name, so `first_token` stays
                        # exactly-once per request — same dedupe
                        # marker as the serve_request event below.
                        tr.transition(
                            st.request.request_id,
                            "xfer" if handoff is not None else "decode",
                            event="first_token"
                            if st.first_token_t is None
                            else "re_prefilled")
                    if st.first_token_t is not None:
                        self._emit(st, tok, events)
                        if handoff is not None and not st.finished:
                            self._commit_handoff(st, handoff)
                        continue
                    st.first_token_t = time.perf_counter()
                    req = st.request
                    reg = obs.get_registry()
                    if reg is not None:
                        ttft = (st.first_token_t - st.submit_t) * 1e3
                        reg.histogram("serve.ttft_ms").observe(ttft)
                        if req.tenant:
                            # the per-tenant aggregate the FrontDoor
                            # SLO policy reads (frontdoor._ttft_p95)
                            reg.histogram(
                                f"serve.tenant[{req.tenant}]"
                                ".ttft_ms").observe(ttft)
                        if st.num_shared:
                            reg.counter("serve.prefix_hits").inc(
                                st.num_shared)
                        misses = len(st.page_keys) - st.num_shared
                        if misses:
                            reg.counter(
                                "serve.prefix_misses").inc(misses)
                    obs.emit_event(
                        "serve_request", id=req.request_id,
                        tenant=req.tenant, adapter=req.adapter,
                        prompt_len=int(req.prompt_ids.size),
                        slot=st.slot, blocks=len(st.blocks),
                        cached_tokens=st.cached_tokens)
                    self._emit(st, tok, events)
                    if handoff is not None and not st.finished:
                        self._commit_handoff(st, handoff)
                except Exception as e:  # noqa: BLE001
                    st.kv_len, st.pending_token = snap[0], snap[1]
                    del st.output_ids[snap[2]:]
                    st.text_len, st.detok_offset = snap[3], snap[4]
                    st.spec_proposed, st.spec_accepted = snap[5], snap[6]
                    # a multi-token (speculative) span may have emitted
                    # part of its acceptance before failing: those
                    # tokens were rewound and will re-emit after
                    # restore, so their events must not ALSO be
                    # delivered from this step (already-fired on_token
                    # callbacks can't be recalled — same caveat as the
                    # hard replica-reset path)
                    rid = st.request.request_id
                    events[:] = [ev for ev in events
                                 if ev.request_id != rid]
                    self._isolate(st, e)

    def _consume_decode(self, st: RequestState, i: int, n: int, nxt,
                        events: List[TokenEvent]) -> None:
        """Consume a decode slot's sample(s): a plain single-token
        decode (non-speculative program, or a spec slot with no
        draft), or the speculative VERIFY — greedy acceptance takes the
        longest draft prefix the per-position samples reproduce, plus
        one bonus token (so a total miss still emits one token, never
        worse than plain decode).  Rolling back the rejected tail is
        kv_len bookkeeping ONLY: the speculative writes sit in pages
        the request already reserved, beyond the new kv_len, where the
        next span overwrites them and attention never reads
        (serving/spec.py)."""
        if nxt.ndim == 1:               # non-speculative program: (B,)
            st.kv_len += 1
            self._emit(st, int(nxt[i]), events)
            return
        row = nxt[i]
        req = st.request
        k = n - 1
        a = 0
        while a < k and int(row[a]) == st.draft[a]:
            a += 1
        # eos-aware emission length, decided BEFORE emitting: an
        # accepted token that IS the eos finishes the request there and
        # the rest of the accepted span is dropped.  (The draft cap
        # already keeps a+1 inside the max_new budget.)
        will = a + 1
        if req.eos_token_id is not None:
            for j in range(will):
                if int(row[j]) == req.eos_token_id:
                    will = j + 1
                    break
        acc = will - 1                  # drafts actually consumed
        st.kv_len += 1 + acc
        if k:
            # PER-REQUEST accounting lands BEFORE emission (the last
            # emitted token may retire the request, and the retire
            # event/trace must carry this span's acceptance) — it is
            # part of the rollback snapshot, so a mid-emission failure
            # rewinds it with the rest of the state
            st.spec_proposed += k
            st.spec_accepted += acc
        for j in range(will):
            self._emit(st, int(row[j]), events)
            if st.finished:
                break                   # safety net: must match `will`
        if k:
            # GLOBAL counters land AFTER emission: they are not in the
            # snapshot, so counting before _emit could raise would
            # double-count this span when isolation re-runs it
            sp = self.spec
            sp.verifies += 1
            sp.proposed += k
            sp.accepted += acc
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("serve.spec.proposed").inc(k)
                if acc:
                    reg.counter("serve.spec.accepted").inc(acc)
                reg.histogram("serve.spec.accept_len").observe(acc)

    def step(self) -> List[TokenEvent]:
        """Admit what fits, run ONE unified ragged step (prefill chunks
        + decode tokens together), retire what finished.  Returns the
        tokens emitted (one per decoded / prompt-completed request).
        Composes :meth:`step_begin` (dispatch) + :meth:`step_finish`
        (device sync + host post-processing).

        Per-request fault isolation (docs/RESILIENCE.md "Serving
        sites"): a host-side failure in one request's bookkeeping —
        admission, CoW, prefill/decode post-processing, or an injected
        ``serve.*`` fault — never tears down the compiled step or the
        other slots.  The victim is rewound to its pre-span snapshot,
        preempted to host RAM, and transparently re-admitted; everyone
        else's events are delivered normally."""
        return self.step_finish(self.step_begin())

    def stream(self):
        """Generator: run ``step()`` until drained, yielding each
        :class:`TokenEvent` as it is produced.  More requests may be
        added while streaming — they join the running batch."""
        while self.has_work():
            for ev in self.step():
                yield ev

    # requires-lock: _lock
    def _begin_drain(self) -> Dict[str, List[int]]:
        """Start a drain capture (shared by :meth:`run` and
        ``FrontDoor.run``): collect requests already finished since the
        last drain, and arm finish-time capture so eviction under
        ``keep_finished`` can't outrun the drain dict.  Pair with
        :meth:`_end_drain` in a finally."""
        drained: Dict[str, List[int]] = {}
        for rid, st in self._states.items():
            if st.finished and not st.drained:
                st.drained = True
                drained[rid] = list(st.output_ids)
        self._drain_capture = drained
        return drained

    # requires-lock: _lock
    def _end_drain(self) -> None:
        self._drain_capture = None

    def run(self) -> Dict[str, List[int]]:
        """Drain everything; returns {request_id: generated token ids}
        for every request finished since the last ``run()`` — including
        (still-retained) requests that finished during manual ``step()``
        calls before this one (staggered admission).  Outputs are
        captured at finish time, so the dict is complete even when more
        than ``keep_finished`` requests retire in one drain."""
        drained = self._begin_drain()
        try:
            while self.has_work():
                self.step()
        finally:
            self._end_drain()
        return drained
