"""Cluster control plane: store-backed membership, leases, evacuation.

Reference: python/paddle/distributed/launch/controllers (master/elastic
controllers) — restart-the-world elasticity for training jobs.  The
serving tier needs the LIVE version: per-host worker loops that keep
decoding through membership churn, with one thin controller that owns
routing and failure handling but never steps an engine.

Design (docs/SERVING.md "Cluster serving"):

- **Workers** (``serving/worker.py``) register with the TCPStore, renew
  an epoch-fenced lease, and pull admissions / KV handoffs / control
  commands from per-worker store queues — no shared driver, so a host
  failure, GC pause, or upgrade is confined to its failure domain.
- **The controller** (:class:`ClusterController`) is the
  ``EngineReplicaSet``/``DisaggReplicaSet`` routing policy lifted behind
  a store-backed membership view: it routes fresh admissions to the
  prefill tier, prefill-complete ``KVHandout`` refs to the decode tier
  (most-free-blocks, the disagg rule), detects dead workers through
  :class:`LeaseMonitor` (the PR-12 ``HeartbeatMonitor`` with dynamic
  membership), and **evacuates** a dead worker's in-flight requests:
  refs whose KV payload already landed in the transport re-route
  token-identically, the rest re-enter admission as a fresh re-prefill
  (PR 8/12 semantics — greedy outputs are token-identical either way).
- **Epoch fencing**: every lease, queue item, command and output record
  carries the worker's registration epoch (a store counter).  A
  paused-then-resumed worker whose lease was revoked fails its next
  CAS renew (:class:`LeaseLost`), aborts without publishing, and
  rejoins under a fresh epoch; its late writes are fenced at collection
  (``cluster_stale_out``) because the assignment moved on.
- **SLO-driven elasticity**: workers publish live status (queue depth,
  free blocks, rolling ``serve.ttft_ms`` p95, ``SLOCapture`` breaches);
  the controller compares tiers and issues typed commands —
  ``role_flip`` (drain → ``engine.role`` attribute write → re-register;
  the compiled programs are role-independent, so ZERO recompiles),
  ``drain`` (scale-down), ``rolling_upgrade`` (drain → hot-swap params
  → rejoin under a new epoch).  Every transition rides the same
  evacuation machinery as a kill, which is what the ``serving-cluster``
  CI gate pins: token-identity and zero recompiles across flips, kills
  and upgrades.

- **The controller is as killable as the workers** (PR 19): ``submit``
  CAS-writes a **durable admission journal** entry
  (``journal/<rid>`` — prompt, params, tenant/adapter, client
  idempotency key) *before* returning, unroutable refs mirror to
  ``pend/<rid>``, and retirement writes a tombstone carrying the
  output — so :meth:`ClusterController._recover` can rebuild the whole
  admission surface from the store and a duplicate idempotency key
  answers with the EXISTING rid/output (exactly-once at the client
  surface).  A :class:`ControllerLease` on the same epoch-fenced CAS
  primitive the workers use makes failover automatic: a standby
  controller constructed with ``follower=True`` watches the lease,
  takes over on staleness (``cluster_takeover``), replays the journal,
  and bumps the **controller epoch** — stamped on every queue item,
  command and assignment — so a zombie controller's late writes are
  fenced by the workers exactly like stale worker epochs are today.
  Request ids are salted with that epoch (``creq-<ctl>-<seq>``), so a
  bounced controller can never re-issue a rid that collides with a
  prior assignment.
- **Scale-up beyond role flips**: with a pluggable
  :class:`WorkerSpawner` attached, the autoscaler spawns a fresh
  worker process (locally: ``python -m paddle_tpu.serving.worker``)
  when an SLO breach persists with both tiers at the flip floor, and
  drains the emptiest worker back out after a sustained idle run.

Store schema (all under ``<prefix>/``, default ``cluster/``)::

    epoch                 global epoch counter (store.add)
    ctl/epoch             controller epoch counter (store.add) — the
                          rid salt + zombie fence token
    ctl/lease             JSON {holder, epoch, t} — CAS-chained by the
                          active controller (ControllerLease)
    journal/<rid>         JSON admission journal entry; retirement
                          overwrites it with a {done, tokens, reason}
                          tombstone, reaped beyond journal_retention
    jkey/<key>            idempotency-key index: key -> rid (CAS once)
    pend/<rid>            JSON mirror of an unroutable pending ref
    workers/<wid>         JSON {role, epoch, pid, state, version}
    lease/<wid>           JSON {epoch, t} — CAS-chained by the worker;
                          the controller revokes with a tombstone
    status/<wid>          JSON load/SLO snapshot (worker, ~1 Hz)
    telemetry/<wid>       JSON mergeable registry snapshot (counters /
                          gauges / histogram SKETCHES — the fleet
                          ``/metrics`` fold; docs/OBSERVABILITY.md
                          "Fleet observability")
    trace/<rid>/<seg>     JSON per-worker ``serve_trace`` segment
                          (worker/role/epoch/clock_offset envelope);
                          the stitcher joins them cross-host
    clock                 JSON {t} — controller wall clock, re-stamped
                          every pump; workers estimate their skew from
                          store round-trips against it
    q/adm/<wid>/…         per-worker admission queue   (StoreQueue)
    q/hoff/<wid>/…        per-worker handoff-ref queue (StoreQueue)
    q/cmd/<wid>/…         per-worker command queue     (StoreQueue)
    q/handoffs/…          global prefill→controller handoff refs
    q/evac/…              global drain/evacuation refs
    assign/<rid>          JSON {wid, epoch, ref} — routing fence
    out/<rid>             JSON {tokens, reason, worker, epoch}
    cmdack/<cid>          JSON {ok, reason} — command acknowledgement
    xfer/…                KV page payloads (``StoreTransport``)

Only the worker half touches jax; this module is host-side bookkeeping
over the store plus the PR-12 transport, so the controller can run on a
CPU-only coordinator host.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..observability.aggregate import (fleet_fold, registry_to_wire,
                                       stitch_trace_segments)
from ..observability.sinks import registry_to_prometheus
from ..resilience import _state as _rs_state
from ..resilience.retry import RetryPolicy
from .disagg import HeartbeatMonitor, StoreTransport

__all__ = ["ClusterController", "ControllerLease", "LeaseMonitor",
           "LeaseLost", "StoreQueue", "WorkerSpawner"]


class LeaseLost(RuntimeError):
    """The worker's lease-renew CAS lost its chain: the controller
    revoked the lease (presumed dead / fenced) or renewal exhausted its
    retries.  The worker must stop acting on its epoch — abort in-flight
    work WITHOUT publishing, clear engine state, and re-register under a
    fresh epoch.  Deliberately not retryable (``retry.DEFAULT_RETRYABLE``
    excludes it): retrying a lost lease is exactly the stale-ownership
    bug the fence exists to prevent."""


# ---------------------------------------------------------------------------
# store-backed primitives
# ---------------------------------------------------------------------------

class StoreQueue:
    """A single-reader FIFO over store keys: ``<base>/tail`` is an
    ``add`` counter allocating sequence numbers, ``<base>/<seq>`` holds
    one JSON item.  The reader owns a local head cursor and deletes
    consumed keys.

    Hole-tolerant: a push is add-then-set, so the reader can observe the
    tail before the item body lands (break and retry next poll), and a
    retried ``add`` whose first reply died with its socket may skip a
    sequence number forever — after ``MISS_LIMIT`` polls the reader
    steps over the hole and counts it (``holes``) instead of wedging the
    queue.

    The head cursor is persisted under ``<base>/head`` after each
    consuming ``pop_all``, so a fresh reader (restarted process) resumes
    exactly where its predecessor stopped — it neither replays consumed
    items nor (by scanning for survivors) races an in-flight push whose
    body hasn't landed yet."""

    MISS_LIMIT = 8

    def __init__(self, store, base: str):
        self.store = store
        self.base = base.rstrip("/")
        self.holes = 0
        self._head: Optional[int] = None
        self._miss: Dict[int, int] = {}

    def _catch_up(self) -> None:
        if self._head is not None:
            return
        raw = self.store.get(f"{self.base}/head")
        self._head = int(raw) if raw else 0

    def push(self, item: dict) -> int:
        seq = self.store.add(f"{self.base}/tail", 1) - 1
        self.store.set(f"{self.base}/{seq}",
                       json.dumps(item).encode())
        return seq

    def pop_all(self) -> List[dict]:
        raw = self.store.get(f"{self.base}/tail")
        tail = int(raw) if raw else 0
        self._catch_up()
        head0 = self._head
        out: List[dict] = []
        while self._head < tail:
            key = f"{self.base}/{self._head}"
            blob = self.store.get(key)
            if blob is None:
                n = self._miss.get(self._head, 0) + 1
                if n < self.MISS_LIMIT:
                    self._miss[self._head] = n
                    break           # in-flight push: retry next poll
                self._miss.pop(self._head, None)
                self.holes += 1     # skipped seq from a retried add
                self._head += 1
                continue
            self._miss.pop(self._head, None)
            self.store.delete(key)
            out.append(json.loads(blob.decode()))
            self._head += 1
        if self._head != head0:
            self.store.set(f"{self.base}/head",
                           str(self._head).encode())
        return out


class LeaseMonitor(HeartbeatMonitor):
    """Dynamic-membership :class:`~paddle_tpu.serving.HeartbeatMonitor`:
    leases double as heartbeats.  A lease value is the worker's
    CAS-chained JSON ``{"epoch": E, "t": wall}``; :meth:`stale_workers`
    applies the same rules as the indexed ``stale()`` — missing means
    not-yet-monitored, present-but-old or unparsable (including the
    controller's revocation tombstone) means dead.  Wall clock, not
    monotonic: the timestamps are compared across processes."""

    def __init__(self, store, *, prefix: str = "cluster/lease",
                 deadline_s: float = 10.0,
                 interval_s: Optional[float] = None, clock=time.time):
        super().__init__(store, 0, prefix=prefix, deadline_s=deadline_s,
                         interval_s=interval_s, clock=clock)

    def lease(self, wid: str) -> Optional[dict]:
        raw = self.store.get(f"{self.prefix}/{wid}")
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return {}               # tombstone / garbage: dead

    def stale_workers(self, wids) -> List[str]:
        out = []
        now = self.clock()
        for wid in wids:
            lease = self.lease(wid)
            if lease is None:
                continue            # never registered: not monitored
            try:
                ts = float(lease["t"])
            except (KeyError, TypeError, ValueError):
                out.append(wid)     # unparsable == dead
                continue
            if now - ts > self.deadline_s:
                out.append(wid)
        return out


class ControllerLease:
    """The controller-side twin of the worker lease: one CAS-chained
    claim on ``<prefix>/ctl/lease`` deciding WHICH controller process
    routes, fails and collects.

    Same primitive, same rules as ``ServingWorker.renew_lease``: the
    holder CAS-chains ``{holder, epoch, t}`` records (expected value is
    its OWN previous write, so any other writer breaks the chain and
    raises :class:`LeaseLost`); a standby judges staleness with the
    lease-monitor rules (absent = free, unparsable = dead, old = dead)
    and :meth:`acquire`\\ s over the observed value — the CAS makes the
    takeover single-winner.  Every acquisition bumps the
    ``ctl/epoch`` counter; the winner stamps that epoch on its queue
    items / commands / assignments, which is what fences the previous
    holder's late writes (workers drop items below the highest
    controller epoch they have seen).

    ``renew`` is interval-gated (``deadline_s / 3``) so the active
    controller can call it every pump without a store round-trip per
    pump."""

    def __init__(self, store, *, prefix: str = "cluster",
                 holder: Optional[str] = None,
                 deadline_s: float = 10.0,
                 interval_s: Optional[float] = None, clock=time.time):
        self.store = store
        self.prefix = prefix.rstrip("/")
        self.key = f"{self.prefix}/ctl/lease"
        self.epoch_key = f"{self.prefix}/ctl/epoch"
        self.holder = holder or \
            f"ctl-{socket.gethostname()}-{os.getpid()}"
        self.deadline_s = float(deadline_s)
        self.interval_s = float(deadline_s) / 3.0 \
            if interval_s is None else float(interval_s)
        self.clock = clock
        self.epoch: Optional[int] = None
        self._val: Optional[bytes] = None
        self._last = 0.0

    def observe(self) -> Optional[dict]:
        """The current lease record (None when absent, ``{}`` when
        unparsable/tombstoned — same vocabulary as the worker
        monitor)."""
        raw = self.store.get(self.key)
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return {}

    def stale(self) -> bool:
        """True when the lease is up for grabs: absent, unparsable, or
        older than ``deadline_s``."""
        lease = self.observe()
        if lease is None:
            return True
        try:
            ts = float(lease["t"])
        except (KeyError, TypeError, ValueError):
            return True
        return self.clock() - ts > self.deadline_s

    def acquire(self) -> int:
        """Claim the lease (fresh start or takeover): allocate a new
        controller epoch and CAS over the observed value.  Raises
        :class:`LeaseLost` when the lease is freshly held by someone
        else, or when another standby won the CAS race."""
        cur = self.store.get(self.key)
        if cur is not None and not self.stale():
            raise LeaseLost(
                f"controller lease freshly held; {self.holder!r} "
                f"cannot acquire")
        epoch = int(self.store.add(self.epoch_key, 1))
        new = json.dumps({"holder": self.holder, "epoch": epoch,
                          "t": self.clock()}).encode()
        if not self.store.compare_set(self.key,
                                      cur if cur is not None else b"",
                                      new):
            raise LeaseLost(
                f"controller lease CAS lost: another standby took "
                f"over before {self.holder!r}")
        self.epoch = epoch
        self._val = new
        self._last = self.clock()
        return epoch

    def renew(self, *, force: bool = False) -> None:
        """CAS-chain the lease (interval-gated).  A broken chain — a
        standby took over while this process was dark — raises
        :class:`LeaseLost`: the caller is a zombie and must stop
        routing immediately."""
        if self._val is None:
            raise LeaseLost(f"{self.holder!r} holds no controller lease")
        now = self.clock()
        if not force and now - self._last < self.interval_s:
            return
        new = json.dumps({"holder": self.holder, "epoch": self.epoch,
                          "t": now}).encode()
        if not self.store.compare_set(self.key, self._val, new):
            self._val = None
            raise LeaseLost(
                f"controller {self.holder!r} lost the lease for epoch "
                f"{self.epoch} (superseded)")
        self._val = new
        self._last = now

    def release(self) -> None:
        """Graceful handover: tombstone the lease so a standby takes
        over immediately instead of waiting out the deadline."""
        if self._val is None:
            return
        self.store.compare_set(self.key, self._val,
                               f"released:{self.epoch}".encode())
        self._val = None


class WorkerSpawner:
    """Scale-up beyond role flips: launches fresh ``serving.worker``
    OS processes for the autoscaler (docs/SERVING.md "Elasticity").

    The default implementation runs ``python -m
    paddle_tpu.serving.worker`` subprocesses on the local host; the
    controller only calls :meth:`spawn` / :meth:`reap`, so a
    deployment substitutes any duck-typed spawner (k8s pod create, MIG
    resize, ...).  A spawned worker *adopts itself*: it registers with
    the store under a fresh epoch like any other worker — the
    controller sees it appear in the membership view and starts
    routing to it, with no side channel."""

    def __init__(self, store_addr: str, factory: str, *,
                 prefix: str = "cluster",
                 python: Optional[str] = None,
                 lease_deadline_s: float = 10.0,
                 extra_args: Tuple[str, ...] = (),
                 env: Optional[dict] = None,
                 cwd: Optional[str] = None):
        self.store_addr = store_addr
        self.factory = factory
        self.prefix = prefix
        self.python = python or sys.executable
        self.lease_deadline_s = float(lease_deadline_s)
        self.extra_args = tuple(extra_args)
        self.env = env
        self.cwd = cwd
        self.procs: Dict[str, subprocess.Popen] = {}
        self._seq = 0

    def spawn(self, role: str) -> str:
        """Launch one worker of ``role``; returns its worker id (the
        spawned process registers under it on its own)."""
        wid = f"spawn-{role}-{os.getpid()}-{self._seq}"
        self._seq += 1
        cmd = [self.python, "-m", "paddle_tpu.serving.worker",
               "--store", self.store_addr, "--role", role,
               "--factory", self.factory, "--worker-id", wid,
               "--prefix", self.prefix,
               "--lease-deadline-s", str(self.lease_deadline_s),
               *self.extra_args]
        self.procs[wid] = subprocess.Popen(
            cmd, env=self.env, cwd=self.cwd)
        return wid

    def reap(self) -> Dict[str, int]:
        """Collect exited spawned processes: ``wid -> returncode``."""
        done = {}
        for wid, p in list(self.procs.items()):
            rc = p.poll()
            if rc is not None:
                done[wid] = rc
                del self.procs[wid]
        return done

    def terminate_all(self, *, timeout_s: float = 10.0) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=timeout_s)
        self.procs.clear()


# ---------------------------------------------------------------------------
# admission wire helpers (shared with serving/worker.py)
# ---------------------------------------------------------------------------

def admission_of(req) -> dict:
    """A scheduler ``Request`` flattened to the JSON the admission
    queues carry — everything a fresh re-prefill needs.  Streaming
    callbacks cannot ride (same rule as ``KVHandout``); greedy outputs
    are identical on re-prefill, sampled ones re-seed."""
    return {"rid": req.request_id,
            "prompt": [int(t) for t in np.asarray(req.prompt_ids).ravel()],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "eos_token_id": req.eos_token_id,
            "tenant": req.tenant,
            "adapter": req.adapter}


def admit_admission(engine, adm: dict) -> str:
    """Queue a flattened admission on ``engine``; duplicate request ids
    surface as ``AdmissionError`` (callers treat that as already-admitted
    and skip — controller re-routes are at-least-once)."""
    return engine.add_request(
        np.asarray(adm["prompt"], np.int32),
        max_new_tokens=int(adm["max_new_tokens"]),
        temperature=float(adm.get("temperature", 0.0)),
        eos_token_id=adm.get("eos_token_id"),
        request_id=adm["rid"],
        tenant=adm.get("tenant"),
        adapter=adm.get("adapter"))


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class ClusterController:
    """Routing + failure handling for a store-registered worker fleet.

    The controller never steps an engine and holds no KV: its whole
    state is the store (assignments, outs, membership) plus local read
    cursors, so a bounced controller process recovers by re-reading
    ``assign/`` and ``out/`` (:meth:`_recover`) while the workers ride
    out the blip under ``TCPStore``'s reconnect-with-retry.

    Drive it with :meth:`pump` (one control round: route queued refs,
    reap stale leases, collect outputs, autoscale) — from a loop, a
    thread, or interleaved with in-process worker ``step()`` calls in
    tests.  ``submit``/``collect`` give it the Engine-shaped
    producer/consumer surface the tests and the gate drive."""

    def __init__(self, store, *, prefix: str = "cluster",
                 lease_deadline_s: float = 10.0, clock=time.time,
                 transport=None, autoscale: bool = False,
                 min_tier: int = 1, flip_queue_ratio: float = 4.0,
                 flip_cooldown_s: float = 5.0,
                 status_stale_s: Optional[float] = None,
                 straggler_factor: float = 3.0,
                 straggler_windows: int = 3,
                 straggler_min_ms: float = 1.0,
                 trace_retention: int = 1024,
                 journal_retention: int = 1024,
                 lease: Optional[ControllerLease] = None,
                 follower: bool = False,
                 retry: Optional[RetryPolicy] = None,
                 spawner: Optional[WorkerSpawner] = None,
                 max_workers: int = 8,
                 spawn_breach_windows: int = 3,
                 scale_down_windows: int = 8,
                 sleep: Callable[[float], None] = time.sleep):
        self.store = store
        self.prefix = prefix.rstrip("/")
        self.clock = clock
        self.transport = transport if transport is not None else \
            StoreTransport(store, prefix=f"{self.prefix}/xfer")
        self.monitor = LeaseMonitor(
            store, prefix=f"{self.prefix}/lease",
            deadline_s=lease_deadline_s, clock=clock)
        self.autoscale = autoscale
        self.min_tier = int(min_tier)
        self.flip_queue_ratio = float(flip_queue_ratio)
        self.flip_cooldown_s = float(flip_cooldown_s)
        self.status_stale_s = float(lease_deadline_s) \
            if status_stale_s is None else float(status_stale_s)
        self.straggler_factor = float(straggler_factor)
        self.straggler_windows = max(1, int(straggler_windows))
        self.straggler_min_ms = float(straggler_min_ms)
        self.trace_retention = int(trace_retention)
        self.journal_retention = int(journal_retention)
        self.lease = lease
        self.follower = bool(follower)
        self.retry = retry if retry is not None else RetryPolicy()
        self.spawner = spawner
        self.max_workers = int(max_workers)
        self.spawn_breach_windows = max(1, int(spawn_breach_windows))
        self.scale_down_windows = max(1, int(scale_down_windows))
        self._sleep = sleep
        self._handoff_q = StoreQueue(store, f"{self.prefix}/q/handoffs")
        self._evac_q = StoreQueue(store, f"{self.prefix}/q/evac")
        self._workers: Dict[str, dict] = {}
        self._status: Dict[str, dict] = {}
        self._assigned: Dict[str, dict] = {}   # rid -> {wid, epoch, ref}
        self._payloads: Dict[str, list] = {}   # rid -> [(xfer key, nbytes)]
        self._outs: Dict[str, dict] = {}
        self._pending: List[dict] = []         # refs with no target yet
        self._pended: set = set()              # rids mirrored to pend/
        self._jkeys: Dict[str, str] = {}       # idempotency key -> rid
        self._cmd_seq = 0
        self._rid_seq = 0
        self._flip_ok_at = 0.0
        self._breach_windows = 0
        self._idle_windows = 0
        self._push_queues: Dict[str, StoreQueue] = {}
        # fleet observability state (docs/OBSERVABILITY.md "Fleet
        # observability"): status-demoted workers (unparsable/stale
        # snapshots — out of routing, still lease-monitored),
        # straggler detection windows, per-(wid, epoch) recompile
        # baselines, a bounded decision log for GET /v1/cluster, and
        # the trace/journal retention queues
        self._status_demoted: set = set()
        self._stragglers: set = set()
        self._straggle_counts: Dict[tuple, int] = {}
        self._compile_base: Dict[tuple, int] = {}
        self._decisions: "collections.deque[dict]" = \
            collections.deque(maxlen=64)
        self._trace_rids: "collections.deque[str]" = collections.deque()
        self._journal_rids: "collections.deque[tuple]" = \
            collections.deque()                # (rid, idempotency key)
        self._http = None
        self._http_thread = None
        # controller epoch: the rid salt + zombie fence token.  A
        # follower allocates nothing — it gets its epoch at takeover;
        # an active controller without a lease (colocated/test drivers)
        # still bumps the counter so a bounced controller can never
        # re-issue a colliding rid.
        self.ctl_epoch: Optional[int] = None
        if self.follower:
            if self.lease is None:
                raise ValueError(
                    "a follower controller needs a ControllerLease "
                    "to watch")
            return
        if self.lease is not None:
            self.ctl_epoch = self.lease.epoch \
                if self.lease.epoch is not None else self.lease.acquire()
        else:
            self.ctl_epoch = int(
                self.store.add(f"{self.prefix}/ctl/epoch", 1))
        self._recover()
        self._publish_clock()

    def _q(self, path: str) -> StoreQueue:
        q = self._push_queues.get(path)
        if q is None:
            q = self._push_queues[path] = StoreQueue(
                self.store, f"{self.prefix}/{path}")
        return q

    # -- producer / consumer surface ---------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int = 16,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None,
               idempotency_key: Optional[str] = None) -> str:
        """Queue one request for the prefill tier; returns its id.
        Routing happens on the next :meth:`pump` if no worker is
        eligible yet (startup races are pending work, not errors).

        Durable before visible: the admission is CAS-journaled to
        ``journal/<rid>`` BEFORE this returns (``cluster.journal``
        fault site, retried under the controller's ``RetryPolicy``;
        exhaustion rejects THIS submission to the caller — nothing was
        journaled, so nothing is half-admitted).  A duplicate
        ``idempotency_key`` returns the EXISTING rid without a second
        admission — the ``jkey/<key>`` index is CAS-created once, so
        concurrent duplicates race to a single winner."""
        if self.follower:
            raise LeaseLost(
                "follower controller cannot admit: it holds no "
                "controller lease (pump() until takeover)")
        if idempotency_key is not None:
            dup = self._jkey_lookup(idempotency_key)
            if dup is not None:
                obs.emit_event("cluster_journal_dup", id=dup,
                               key=idempotency_key)
                return dup
        if request_id is None:
            request_id = f"creq-{self.ctl_epoch}-{self._rid_seq}"
            self._rid_seq += 1
        adm = {"rid": request_id,
               "prompt": [int(t) for t in
                          np.asarray(prompt_ids).ravel()],
               "max_new_tokens": int(max_new_tokens),
               "temperature": float(temperature),
               "eos_token_id": eos_token_id,
               "tenant": tenant, "adapter": adapter,
               "key": idempotency_key}
        rid = self._journal(request_id, adm, idempotency_key)
        if rid != request_id:
            # lost the idempotency-key race to a concurrent duplicate
            obs.emit_event("cluster_journal_dup", id=rid,
                           key=idempotency_key)
            return rid
        if idempotency_key is not None:
            self._jkeys[idempotency_key] = rid
        self._route({"rid": request_id, "xfer": None, "adm": adm,
                     "from": "controller"})
        return request_id

    def _jkey_lookup(self, key: str) -> Optional[str]:
        rid = self._jkeys.get(key)
        if rid is not None:
            return rid
        raw = self.store.get(f"{self.prefix}/jkey/{key}")
        if raw is None:
            return None
        rid = raw.decode()
        self._jkeys[key] = rid
        return rid

    def _journal(self, rid: str, adm: dict,
                 key: Optional[str]) -> str:
        """CAS-write the admission journal entry (and the idempotency
        index) before ``submit`` returns.  Returns the rid that OWNS
        the idempotency key — ours, or the concurrent winner's."""
        def attempt():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("cluster.journal")
            if key is not None and not self.store.compare_set(
                    f"{self.prefix}/jkey/{key}", b"", rid.encode()):
                raw = self.store.get(f"{self.prefix}/jkey/{key}")
                owner = raw.decode() if raw is not None else None
                if owner is not None and owner != rid:
                    return owner
            entry = {"adm": adm, "key": key, "ctl": self.ctl_epoch,
                     "t": self.clock()}
            self.store.compare_set(f"{self.prefix}/journal/{rid}",
                                   b"", json.dumps(entry).encode())
            return rid

        return self.retry.run(attempt, site="cluster.journal")

    @property
    def outputs(self) -> Dict[str, dict]:
        """Collected output records: ``rid -> {tokens, reason, worker,
        epoch}`` (fenced — only the live assignment's write counts)."""
        return dict(self._outs)

    def collect(self, request_id: str, *, timeout_s: float = 30.0,
                poll_s: float = 0.005,
                advance: Optional[Callable[[], None]] = None) -> dict:
        """Pump until ``request_id``'s output lands (or raise
        ``TimeoutError``).  ``advance`` runs every poll — in-process
        tests pass a closure stepping their workers; cross-process
        deployments leave it None and the workers make progress on
        their own."""
        deadline = self.clock() + timeout_s
        while True:
            if request_id in self._outs:
                return self._outs[request_id]
            if advance is not None:
                advance()
            self.pump()
            if request_id in self._outs:
                return self._outs[request_id]
            if self.clock() > deadline:
                raise TimeoutError(
                    f"no output for {request_id!r} within {timeout_s}s "
                    f"(assigned: {self._assigned.get(request_id)})")
            self._sleep(poll_s)

    # -- membership --------------------------------------------------------

    def members(self, *, refresh: bool = True) -> Dict[str, dict]:
        """``wid -> record`` for every registered worker (any state)."""
        if refresh:
            base = f"{self.prefix}/workers/"
            recs = {}
            for key in self.store.keys(base):
                raw = self.store.get(key)
                if raw is None:
                    continue
                try:
                    recs[key[len(base):]] = json.loads(raw.decode())
                except (ValueError, UnicodeDecodeError):
                    continue
            self._workers = recs
        return dict(self._workers)

    def wait_for_workers(self, n: int, *, timeout_s: float = 60.0,
                         role: Optional[str] = None) -> List[str]:
        """Block until ``n`` workers (optionally of ``role``) are up."""
        deadline = self.clock() + timeout_s
        while True:
            up = [w for w, r in self.members().items()
                  if r.get("state") == "up"
                  and (role is None or r.get("role") == role)]
            if len(up) >= n:
                return sorted(up)
            if self.clock() > deadline:
                raise TimeoutError(
                    f"only {len(up)}/{n} workers up within {timeout_s}s")
            self._sleep(0.02)

    def _live(self, role: Optional[str] = None) -> List[str]:
        return [w for w, r in self._workers.items()
                if r.get("state") == "up"
                and (role is None or r.get("role") in (role, "both"))]

    def _routable(self, role: Optional[str] = None) -> List[str]:
        """Routing candidates: live AND not status-demoted.  Demotion
        only narrows routing — the lease monitor stays the authority on
        death, so a worker with a healthy lease but a wedged status
        publisher keeps its lease and rejoins routing on its next good
        snapshot."""
        return [w for w in self._live(role)
                if w not in self._status_demoted]

    def _demote_status(self, wid: str, why: str) -> None:
        if wid in self._status_demoted:
            return
        self._status_demoted.add(wid)
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.status_demotions").inc()
        obs.emit_event("cluster_status_demoted", worker=wid, reason=why)

    def _refresh_status(self) -> None:
        """Pull every registered worker's status snapshot.  A snapshot
        that is present but unparsable, or whose stamp is older than
        ``status_stale_s``, is treated like a stale heartbeat: the
        worker is DEMOTED from routing (plus one
        ``cluster_status_demoted`` event), never silently kept as the
        last good reading — routing on a frozen ``free_blocks`` is how
        a wedged worker becomes a black hole.  A missing key means
        not-yet-published (startup), same rule as the lease monitor's
        never-registered case."""
        now = self.clock()
        for wid in self._workers:
            raw = self.store.get(f"{self.prefix}/status/{wid}")
            if raw is None:
                continue
            try:
                st = json.loads(raw.decode())
                ts = float(st["t"])
            except (KeyError, TypeError, ValueError,
                    UnicodeDecodeError):
                self._demote_status(wid, "unparsable")
                continue
            if now - ts > self.status_stale_s:
                self._demote_status(wid, "stale")
                continue
            if wid in self._status_demoted:
                self._status_demoted.discard(wid)
                obs.emit_event("cluster_status_recovered", worker=wid)
            self._status[wid] = st
        if obs.get_telemetry() is not None:
            self._scan_anomalies()

    # -- fleet anomaly detection -------------------------------------------

    def _scan_anomalies(self) -> None:
        """Status-driven fleet anomaly pass (one falsy check upstream —
        never runs with telemetry disabled).

        Stragglers: a worker whose rolling ``ttft_p95``/``step_p95``
        exceeds ``straggler_factor`` × the median of its TIER PEERS for
        ``straggler_windows`` consecutive refreshes is flagged
        (``cluster_straggler``) and counted as an SLO breach by
        :meth:`_tier_breached`, feeding the autoscaler's flip
        heuristic.  The median is over the OTHER workers so a 2-worker
        tier can still convict (a worker can never be 3× a median its
        own sample dominates).

        Recompile escalation: any worker's recompile sentinel count
        rising after its first status of the epoch (post-warmup by
        construction — workers warm up before registering) raises
        ``cluster_recompile_alert`` once per new compile observed."""
        reg = obs.get_registry()
        for wid, st in self._status.items():
            c = st.get("compiles")
            if c is None:
                continue
            key = (wid, st.get("epoch"))
            base = self._compile_base.get(key)
            if base is None:
                self._compile_base[key] = c
            elif c > base:
                self._compile_base[key] = c
                obs.emit_event("cluster_recompile_alert", worker=wid,
                               epoch=st.get("epoch"), compiles=c,
                               new=c - base)
                if reg is not None:
                    reg.counter("cluster.recompile_alerts").inc(c - base)
        flagged: set = set()
        for role in ("prefill", "decode"):
            wids = [w for w in self._live(role) if w in self._status]
            for metric in ("ttft_p95", "step_p95"):
                vals = {}
                for w in wids:
                    v = self._status[w].get(metric)
                    if isinstance(v, (int, float)):
                        vals[w] = float(v)
                if len(vals) < 2:
                    continue
                for w, v in vals.items():
                    others = sorted(x for ww, x in vals.items()
                                    if ww != w)
                    med = others[len(others) // 2]
                    bar = self.straggler_factor \
                        * max(med, self.straggler_min_ms)
                    key = (w, metric)
                    if v > bar:
                        n = self._straggle_counts.get(key, 0) + 1
                        self._straggle_counts[key] = n
                        if n >= self.straggler_windows:
                            flagged.add(w)
                    else:
                        self._straggle_counts.pop(key, None)
        for w in flagged - self._stragglers:
            if reg is not None:
                reg.counter("cluster.stragglers").inc()
            obs.emit_event("cluster_straggler", worker=w,
                           role=self._workers.get(w, {}).get("role"),
                           ttft_p95=self._status.get(w, {})
                           .get("ttft_p95"),
                           step_p95=self._status.get(w, {})
                           .get("step_p95"))
            self._decisions.append(
                {"t": self.clock(), "kind": "straggler", "worker": w})
        for w in self._stragglers - flagged:
            obs.emit_event("cluster_straggler_recovered", worker=w)
        self._stragglers = flagged

    # -- routing -----------------------------------------------------------

    def _pick(self, tier: str) -> Optional[str]:
        """Healthiest eligible worker: decode refs go to most free
        blocks (the disagg rule — a restore needs contiguous budget),
        admissions to the shallowest prefill queue.  Deterministic
        (ties break on wid) so chaos runs replay.  Status-demoted
        workers are excluded (:meth:`_routable`) — routing needs a
        fresh load snapshot; falls back to the full live set when the
        whole tier is demoted (a slow worker beats a dropped ref)."""
        cands = self._routable(tier) or self._live(tier)
        if not cands:
            return None

        def load(w):
            s = self._status.get(w, {})
            return (s.get("queue_depth", 0) + s.get("active", 0),
                    -s.get("free_blocks", 0), w)

        if tier == "decode":
            return min(cands, key=lambda w: (
                -self._status.get(w, {}).get("free_blocks", 0),
                self._status.get(w, {}).get("queue_depth", 0), w))
        return min(cands, key=load)

    def _route(self, ref: dict) -> bool:
        """Route one ref: a KV handoff (``xfer`` set) to the decode
        tier — unless the snapshot is mid-prefill, which resumes on the
        prefill tier — and a bare admission to the prefill tier.
        Unroutable refs pend for the next pump."""
        tier = "decode" if ref.get("xfer") and not ref.get("prefilling") \
            else "prefill"
        rid = ref["rid"]
        wid = self._pick(tier)
        if wid is None:
            # store-backed pending: a controller that dies here leaves
            # the ref recoverable under pend/<rid> (journal entries
            # cover bare admissions; this covers unroutable HANDOFF
            # refs whose queue item was already consumed)
            self._pending.append(ref)
            if rid not in self._pended:
                self._pended.add(rid)
                self.store.set(f"{self.prefix}/pend/{rid}",
                               json.dumps(ref).encode())
            return False
        rec = self._workers[wid]
        item = dict(ref, wid=wid, epoch=rec.get("epoch"),
                    ctl=self.ctl_epoch)
        q = "hoff" if ref.get("xfer") else "adm"
        self._q(f"q/{q}/{wid}").push(item)
        assign = {"wid": wid, "epoch": rec.get("epoch"), "ref": ref,
                  "ctl": self.ctl_epoch}
        self._assigned[rid] = assign
        self.store.set(f"{self.prefix}/assign/{rid}",
                       json.dumps(assign).encode())
        if rid in self._pended:
            self._pended.discard(rid)
            self.store.delete(f"{self.prefix}/pend/{rid}")
        if ref.get("xfer"):
            pl = self._payloads.setdefault(rid, [])
            ent = (ref["xfer"], int(ref.get("nbytes", 0)))
            if ent not in pl:
                pl.append(ent)
        obs.emit_event("cluster_route", id=rid, worker=wid, tier=tier,
                       xfer=bool(ref.get("xfer")))
        return True

    # -- control commands --------------------------------------------------

    def _command(self, wid: str, cmd: dict) -> str:
        rec = self._workers.get(wid) or self.members().get(wid)
        if rec is None:
            raise KeyError(f"unknown worker {wid!r}")
        cid = f"cmd-{self.ctl_epoch}-{self._cmd_seq}"
        self._cmd_seq += 1
        item = dict(cmd, id=cid, epoch=rec.get("epoch"),
                    ctl=self.ctl_epoch)
        self._q(f"q/cmd/{wid}").push(item)
        obs.emit_event("cluster_command", worker=wid, id=cid,
                       kind=cmd.get("kind"), epoch=rec.get("epoch"))
        return cid

    def role_flip(self, wid: str, role: str) -> str:
        """Drain ``wid`` and re-register it as ``role`` — the elasticity
        primitive.  Zero recompiles: the worker's compiled programs are
        role-independent; the flip is an attribute write between
        epochs."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"role_flip target must be prefill/decode, "
                             f"got {role!r}")
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.role_flips").inc()
        return self._command(wid, {"kind": "role_flip", "role": role})

    def drain_worker(self, wid: str) -> str:
        """Graceful scale-down: evacuate and deregister ``wid``."""
        return self._command(wid, {"kind": "drain"})

    def rolling_upgrade(self, wid: str, version: str) -> str:
        """Drain → hot-swap params (the worker's ``param_source``) →
        rejoin under a new lease epoch."""
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.upgrades").inc()
        return self._command(wid, {"kind": "rolling_upgrade",
                                   "version": version})

    def command_ack(self, cid: str) -> Optional[dict]:
        raw = self.store.get(f"{self.prefix}/cmdack/{cid}")
        return json.loads(raw.decode()) if raw is not None else None

    # -- failure detection + evacuation ------------------------------------

    def _fail_worker(self, wid: str, *, reason: str = "lease_expired"
                     ) -> int:
        """Declare ``wid`` dead: revoke its lease (tombstone — the
        worker's next CAS renew raises :class:`LeaseLost`, fencing a
        paused-then-resumed process out of its old epoch) and re-route
        every unfinished assignment.  Refs whose payload already landed
        in the transport move token-identically; the rest re-enter
        admission as a fresh re-prefill."""
        rec = self._workers.get(wid, {})
        epoch = rec.get("epoch")
        self.store.set(f"{self.prefix}/lease/{wid}",
                       f"revoked:{epoch}".encode())
        rec = dict(rec, state="dead")
        self._workers[wid] = rec
        self.store.set(f"{self.prefix}/workers/{wid}",
                       json.dumps(rec).encode())
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.deaths").inc()
        obs.emit_event("cluster_dead", worker=wid, epoch=epoch,
                       reason=reason)
        moved = 0
        for rid, a in list(self._assigned.items()):
            if a.get("wid") != wid or rid in self._outs:
                continue
            self._route(a["ref"])
            moved += 1
        if reg is not None and moved:
            reg.counter("cluster.evacuated").inc(moved)
        obs.emit_event("cluster_evacuate", worker=wid, moved=moved,
                       by="controller", reason=reason)
        self._decisions.append(
            {"t": self.clock(), "kind": "evacuate", "worker": wid,
             "reason": reason, "moved": moved})
        return moved

    # -- output collection -------------------------------------------------

    def _collect_outs(self) -> int:
        got = 0
        for rid, a in list(self._assigned.items()):
            if rid in self._outs:
                continue
            raw = self.store.get(f"{self.prefix}/out/{rid}")
            if raw is None:
                continue
            try:
                out = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if out.get("worker") != a.get("wid") \
                    or out.get("epoch") != a.get("epoch"):
                # a fenced write from a stale epoch: the assignment
                # moved on — drop it so the live worker's record lands
                obs.emit_event("cluster_stale_out", id=rid,
                               worker=out.get("worker"),
                               epoch=out.get("epoch"),
                               expected=a.get("wid"))
                self.store.delete(f"{self.prefix}/out/{rid}")
                continue
            self._outs[rid] = out
            self.store.delete(f"{self.prefix}/out/{rid}")
            for key, nbytes in self._payloads.pop(rid, []):
                try:
                    self.transport.discard(key, nbytes)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            got += 1
            self._retire_journal(rid, a, out)
            # trace retention: keep segments for the last
            # ``trace_retention`` finished requests (GET /v1/requests),
            # reap the oldest beyond that so trace/ keys stay bounded
            self._trace_rids.append(rid)
            while len(self._trace_rids) > self.trace_retention:
                old = self._trace_rids.popleft()
                for key in self.store.keys(
                        f"{self.prefix}/trace/{old}/"):
                    self.store.delete(key)
        return got

    def _retire_journal(self, rid: str, assign: dict, out: dict) -> None:
        """Retirement tombstone: overwrite ``journal/<rid>`` with the
        collected output, so a takeover (or a duplicate idempotency
        key) can answer with the finished tokens without the worker —
        and reap the oldest retired entries (journal + jkey index +
        assign record) beyond ``journal_retention``, bounding the
        store's key count under sustained churn."""
        ref = assign.get("ref") or {}
        adm = ref.get("adm") or {}
        key = adm.get("key")
        tomb = {"done": True, "key": key,
                "tokens": out.get("tokens"),
                "reason": out.get("reason"),
                "worker": out.get("worker"), "epoch": out.get("epoch"),
                "tenant": out.get("tenant"),
                "ctl": self.ctl_epoch, "t": self.clock()}
        self.store.set(f"{self.prefix}/journal/{rid}",
                       json.dumps(tomb).encode())
        self._journal_rids.append((rid, key))
        while len(self._journal_rids) > self.journal_retention:
            old_rid, old_key = self._journal_rids.popleft()
            self.store.delete(f"{self.prefix}/journal/{old_rid}")
            self.store.delete(f"{self.prefix}/assign/{old_rid}")
            if old_key is not None:
                self.store.delete(f"{self.prefix}/jkey/{old_key}")
                self._jkeys.pop(old_key, None)

    # -- fleet observability surface ---------------------------------------

    def _publish_clock(self) -> None:
        """Re-stamp ``<prefix>/clock`` with the controller's wall clock
        (every pump).  Workers estimate their skew from store
        round-trips against it (``ServingWorker._sync_clock``); the
        stitcher subtracts that offset so cross-host segment starts
        order correctly.  One falsy check — free when disabled."""
        if obs.get_telemetry() is None:
            return
        self.store.set(f"{self.prefix}/clock",
                       json.dumps({"t": self.clock()}).encode())

    def fleet_registry(self):
        """Fold every worker's published telemetry snapshot
        (``telemetry/<wid>``) plus the controller's own registry
        (pseudo-worker ``controller``) into one
        :class:`~paddle_tpu.observability.aggregate.FleetRegistry`:
        per-worker labelled series, per-role tier rollups, and
        unlabelled fleet rollups whose p95s come from MERGED histogram
        sketches — never from averaging per-worker p95s.  Snapshots are
        fetched on demand (scrape time), not per pump, so an unscraped
        controller pays nothing."""
        snaps: Dict[str, dict] = {}
        for wid in self.members():
            raw = self.store.get(f"{self.prefix}/telemetry/{wid}")
            if raw is None:
                continue
            try:
                snap = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if isinstance(snap.get("metrics"), dict):
                # the hbm block (ServingWorker.publish_telemetry) folds
                # like any other gauge family: per-worker serve.hbm.*
                # series on the one fleet /metrics surface.  setdefault
                # — a registry-carried series of the same name wins.
                hbm = snap.get("hbm")
                if isinstance(hbm, dict):
                    for k, v in hbm.items():
                        if isinstance(v, (int, float)):
                            snap["metrics"].setdefault(
                                f"serve.hbm.{k}",
                                {"kind": "gauge", "value": v})
                snaps[wid] = snap
        reg = obs.get_registry()
        if reg is not None:
            snaps["controller"] = {"role": "controller",
                                   "metrics": registry_to_wire(reg)}
        return fleet_fold(snaps)

    def metrics_text(self) -> str:
        """Prometheus text exposition of the fleet fold — the body of
        ``GET /metrics``.  Controller-local bookkeeping gauges ride as
        ``extra`` so the surface is never empty mid-startup."""
        return registry_to_prometheus(
            self.fleet_registry(),
            extra={"cluster.live_workers": len(self._live()),
                   "cluster.pending_refs": len(self._pending),
                   "cluster.collected_outputs": len(self._outs)})

    def cluster_view(self) -> dict:
        """The ``GET /v1/cluster`` body: membership with lease/status
        health, routing demotions, stragglers, and the recent decision
        log (evacuations, autoscale flips)."""
        self.members()
        now = self.clock()
        raw = self.store.get(f"{self.prefix}/epoch")
        workers = {}
        for wid, rec in self._workers.items():
            lease = self.monitor.lease(wid)
            workers[wid] = {
                **rec,
                "lease": lease,
                "lease_age_s": (round(now - float(lease["t"]), 3)
                                if lease and "t" in lease else None),
                "status": self._status.get(wid),
                "status_demoted": wid in self._status_demoted,
                "straggler": wid in self._stragglers,
            }
        return {"t": now,
                "epoch": int(raw) if raw else 0,
                "ctl_epoch": self.ctl_epoch,
                "follower": self.follower,
                "journaled": len(self._journal_rids),
                "workers": workers,
                "autoscale": self.autoscale,
                "assigned": len(self._assigned),
                "outputs": len(self._outs),
                "pending": len(self._pending),
                "decisions": list(self._decisions)}

    def trace_segments(self, rid: str) -> List[dict]:
        """Every published per-worker trace segment for ``rid``
        (unstitched, in store-key order)."""
        segs = []
        for key in sorted(self.store.keys(
                f"{self.prefix}/trace/{rid}/")):
            raw = self.store.get(key)
            if raw is None:
                continue
            try:
                segs.append(json.loads(raw.decode()))
            except (ValueError, UnicodeDecodeError):
                continue
        return segs

    def request_timeline(self, rid: str) -> Optional[dict]:
        """The ``GET /v1/requests/<rid>`` body: ``rid``'s per-worker
        segments federated from the store and stitched into one
        cross-host timeline (skew-corrected ordering, inter-segment
        gaps attributed to xfer — see
        ``observability.aggregate.stitch_trace_segments``).  None when
        no worker published a segment."""
        return stitch_trace_segments(self.trace_segments(rid))

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the controller's stdlib HTTP surface on a daemon
        thread; returns the bound ``(host, port)``.

        Endpoints (docs/OBSERVABILITY.md "Fleet observability"):

        - ``GET /metrics``      Prometheus fleet fold (text 0.0.4)
        - ``GET /v1/cluster``   membership / leases / decisions JSON
        - ``GET /v1/requests/<rid>``  stitched cross-host timeline
        - ``GET /healthz``      liveness probe
        """
        if self._http is not None:
            return self._http.server_address
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        ctl = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 — stdlib name
                pass

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — stdlib name
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(200, ctl.metrics_text(),
                                   "text/plain; version=0.0.4")
                    elif path == "/healthz":
                        self._send(200, "ok\n", "text/plain")
                    elif path == "/v1/cluster":
                        self._send(200, json.dumps(ctl.cluster_view()),
                                   "application/json")
                    elif path.startswith("/v1/requests/"):
                        rid = path[len("/v1/requests/"):]
                        tl = ctl.request_timeline(rid)
                        if tl is None:
                            self._send(404, json.dumps(
                                {"error": "no trace", "id": rid}),
                                "application/json")
                        else:
                            self._send(200, json.dumps(tl),
                                       "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": "not found", "path": path}),
                            "application/json")
                except Exception as e:  # noqa: BLE001 — surface, don't die
                    try:
                        self._send(500, json.dumps(
                            {"error": repr(e)}), "application/json")
                    except Exception:  # noqa: BLE001
                        pass

        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, kwargs={"poll_interval": 0.1},
            name="cluster-http", daemon=True)
        self._http_thread.start()
        obs.emit_event("cluster_http", host=self._http.server_address[0],
                       port=self._http.server_address[1])
        return self._http.server_address

    def close_http(self) -> None:
        if self._http is None:
            return
        self._http.shutdown()
        self._http.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
        self._http = None
        self._http_thread = None

    # -- elasticity --------------------------------------------------------

    def _tier_load(self, wids) -> float:
        return sum(self._status.get(w, {}).get("queue_depth", 0)
                   + self._status.get(w, {}).get("active", 0)
                   for w in wids)

    def _tier_breached(self, wids) -> bool:
        # a convicted straggler counts as a breach: the anomaly scan
        # feeds the same flip heuristic the SLOCapture breach does
        return any(self._status.get(w, {}).get("slo_breached")
                   or w in self._stragglers
                   for w in wids)

    def _autoscale(self) -> Optional[str]:
        """One SLO/load-driven rebalance decision per cooldown window:
        when a tier is starved (queue imbalance beyond
        ``flip_queue_ratio``, or breaching its TTFT SLO while the other
        tier is healthy) and the donor tier can spare a worker
        (``min_tier``), flip the donor's idlest worker over.  The flip
        itself is the same drain→re-register evacuation as a kill.

        With a :class:`WorkerSpawner` attached, two more moves open up
        beyond role flips: when the breach PERSISTS
        (``spawn_breach_windows`` consecutive evaluations) with the
        donor tier already at the flip floor, SPAWN a fresh worker for
        the hot tier (it registers and adopts itself into the
        membership view); and after ``scale_down_windows`` consecutive
        fully-idle, breach-free evaluations, DRAIN the emptiest worker
        of the larger tier back out — the same graceful evacuation as
        a ``drain`` command."""
        if not self.autoscale or self.clock() < self._flip_ok_at:
            return None
        pre, dec = self._live("prefill"), self._live("decode")
        if not pre or not dec:
            return None
        pre_load, dec_load = self._tier_load(pre), self._tier_load(dec)
        pre_hot = pre_load > self.flip_queue_ratio * max(dec_load, 1) \
            or (self._tier_breached(pre) and not self._tier_breached(dec))
        dec_hot = dec_load > self.flip_queue_ratio * max(pre_load, 1) \
            or (self._tier_breached(dec) and not self._tier_breached(pre))

        def idlest(wids):
            return min(wids, key=lambda w: (
                self._status.get(w, {}).get("queue_depth", 0)
                + self._status.get(w, {}).get("active", 0), w))

        if pre_hot or dec_hot:
            self._idle_windows = 0
        if pre_hot and pre_load > len(pre) and len(dec) > self.min_tier:
            wid = idlest(dec)
            self.role_flip(wid, "prefill")
        elif dec_hot and dec_load > len(dec) and len(pre) > self.min_tier:
            wid = idlest(pre)
            self.role_flip(wid, "decode")
        elif (pre_hot or dec_hot) and self.spawner is not None:
            # both tiers at the flip floor: a flip would just move the
            # starvation.  Require the breach to persist before paying
            # for a fresh worker process.
            self._breach_windows += 1
            if self._breach_windows < self.spawn_breach_windows \
                    or len(self._live()) >= self.max_workers:
                return None
            role = "prefill" if pre_hot else "decode"
            wid = self.spawner.spawn(role)
            self._breach_windows = 0
            self._flip_ok_at = self.clock() + self.flip_cooldown_s
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("cluster.spawns").inc()
            self._decisions.append(
                {"t": self.clock(), "kind": "spawn", "worker": wid,
                 "role": role, "prefill_load": pre_load,
                 "decode_load": dec_load})
            obs.emit_event("cluster_spawn", worker=wid, role=role,
                           prefill_load=pre_load, decode_load=dec_load)
            return wid
        elif self.spawner is not None and pre_load + dec_load == 0 \
                and not self._tier_breached(pre) \
                and not self._tier_breached(dec):
            self._breach_windows = 0
            self._idle_windows += 1
            if self._idle_windows < self.scale_down_windows:
                return None
            donor = dec if len(dec) > len(pre) else pre
            if len(donor) <= self.min_tier:
                return None
            wid = idlest(donor)
            self.drain_worker(wid)
            self._idle_windows = 0
            self._flip_ok_at = self.clock() + self.flip_cooldown_s
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("cluster.scale_downs").inc()
            self._decisions.append(
                {"t": self.clock(), "kind": "scale_down",
                 "worker": wid})
            obs.emit_event("cluster_scale_down", worker=wid)
            return wid
        else:
            return None
        self._flip_ok_at = self.clock() + self.flip_cooldown_s
        self._decisions.append(
            {"t": self.clock(), "kind": "autoscale", "worker": wid,
             "prefill_load": pre_load, "decode_load": dec_load})
        obs.emit_event("cluster_autoscale", worker=wid,
                       prefill_load=pre_load, decode_load=dec_load)
        return wid

    # -- the control round -------------------------------------------------

    def pump(self) -> Dict[str, int]:
        """One control round: refresh membership/status, route queued
        handoff + evacuation refs (and anything pending), reap stale
        leases into evacuation, collect fenced outputs, autoscale.

        With a :class:`ControllerLease` attached, every round first
        renews it (interval-gated) — a broken chain raises
        :class:`LeaseLost` and this controller must stop: it is the
        zombie now, and its late writes are fenced by the new
        controller's epoch.  In ``follower`` mode the round only
        watches the lease and takes over when it goes stale."""
        if self.follower:
            return self._follow()
        if self.lease is not None:
            try:
                self.lease.renew()
            except LeaseLost:
                obs.emit_event("cluster_fenced", ctl=self.ctl_epoch,
                               holder=self.lease.holder)
                raise
        self._publish_clock()
        self.members()
        self._refresh_status()
        routed = 0
        pending, self._pending = self._pending, []
        for ref in pending:
            routed += bool(self._route(ref))
        for ref in self._handoff_q.pop_all():
            routed += bool(self._route(ref))
        for ref in self._evac_q.pop_all():
            routed += bool(self._route(ref))
        reaped = 0
        for wid in self.monitor.stale_workers(self._live()):
            self._fail_worker(wid)
            reaped += 1
        got = self._collect_outs()
        self._autoscale()
        reg = obs.get_registry()
        if reg is not None:
            reg.gauge("cluster.workers").set(len(self._live()))
            reg.gauge("cluster.pending").set(len(self._pending))
        return {"routed": routed, "reaped": reaped, "collected": got,
                "pending": len(self._pending)}

    # -- failover ----------------------------------------------------------

    def _follow(self) -> Dict[str, int]:
        """One follower round: watch the controller lease; when it
        goes stale, take over — single CAS winner, fresh controller
        epoch, full rebuild from journal + ``assign/`` + ``pend/``.
        The ``cluster.takeover`` fault site fires after staleness is
        observed and before the CAS: a fault aborts the attempt
        cleanly and the follower retries next pump."""
        idle = {"routed": 0, "reaped": 0, "collected": 0,
                "pending": 0, "follower": 1}
        if not self.lease.stale():
            return idle
        try:
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi("cluster.takeover")
            epoch = self.lease.acquire()
        except LeaseLost:
            return idle             # another standby won the race
        except Exception as e:  # noqa: BLE001 — injected/host fault
            obs.emit_event("cluster_takeover_retry",
                           holder=self.lease.holder,
                           exc=type(e).__name__)
            return idle
        self.follower = False
        self.ctl_epoch = epoch
        self._assigned.clear()
        self._payloads.clear()
        self._outs.clear()
        self._pending = []
        self._pended = set()
        self._jkeys = {}
        self._journal_rids.clear()
        self._recover()
        self._publish_clock()
        reg = obs.get_registry()
        if reg is not None:
            reg.counter("cluster.takeovers").inc()
        obs.emit_event("cluster_takeover", ctl=epoch,
                       holder=self.lease.holder,
                       assigned=len(self._assigned),
                       pending=len(self._pending))
        self._decisions.append(
            {"t": self.clock(), "kind": "takeover", "ctl": epoch,
             "holder": self.lease.holder})
        return self.pump()          # first active round immediately

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        """Rebuild admission state from the store after a controller
        restart or takeover: ``assign/`` holds the routed surface,
        ``journal/`` the admitted one, ``pend/`` the unroutable refs.
        ``out/`` keys are collected on the next pump.  Journaled but
        never-assigned entries — the exact submit-returned/not-yet-
        routed crash window — are re-routed as fresh admissions;
        retirement tombstones repopulate the collected outputs (and
        the idempotency index), so duplicate keys still answer with
        the finished tokens.  Re-routing an already-assigned rid just
        updates its assignment (workers skip duplicate admissions)."""
        base = f"{self.prefix}/assign/"
        for key in self.store.keys(base):
            raw = self.store.get(key)
            if raw is None:
                continue
            try:
                a = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            rid = key[len(base):]
            self._assigned[rid] = a
            ref = a.get("ref") or {}
            if ref.get("xfer"):
                self._payloads.setdefault(rid, []).append(
                    (ref["xfer"], int(ref.get("nbytes", 0))))
        jbase = f"{self.prefix}/journal/"
        replayed = finished = 0
        for key in sorted(self.store.keys(jbase)):
            raw = self.store.get(key)
            if raw is None:
                continue
            try:
                entry = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            rid = key[len(jbase):]
            jkey = entry.get("key")
            if jkey is not None:
                self._jkeys[jkey] = rid
            if entry.get("done"):
                finished += 1
                self._journal_rids.append((rid, jkey))
                if rid not in self._outs:
                    self._outs[rid] = {
                        "tokens": entry.get("tokens"),
                        "reason": entry.get("reason"),
                        "worker": entry.get("worker"),
                        "epoch": entry.get("epoch"),
                        "tenant": entry.get("tenant")}
                continue
            if rid in self._assigned:
                continue            # routed before the crash
            adm = entry.get("adm")
            if adm is not None:
                self._route({"rid": rid, "xfer": None, "adm": adm,
                             "from": "journal"})
                replayed += 1
        pbase = f"{self.prefix}/pend/"
        pended = 0
        for key in sorted(self.store.keys(pbase)):
            rid = key[len(pbase):]
            raw = self.store.get(key)
            if rid in self._assigned or rid in self._outs \
                    or raw is None:
                self.store.delete(key)
                continue
            try:
                ref = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                self.store.delete(key)
                continue
            if rid in self._pended \
                    or any(p.get("rid") == rid for p in self._pending):
                continue            # journal replay already pended it
            self._pended.add(rid)
            self._pending.append(ref)
            pended += 1
        if replayed or finished or pended:
            reg = obs.get_registry()
            if reg is not None:
                reg.counter("cluster.journal_replayed").inc(replayed)
            obs.emit_event("cluster_journal_replay", ctl=self.ctl_epoch,
                           replayed=replayed, finished=finished,
                           pended=pended, assigned=len(self._assigned))
