"""Checkpointing: ``save``/``load`` parity plus distributed sharded
checkpoints with reshard-on-load.

Reference surface (SURVEY.md §5.4):
- python/paddle/framework/io.py — ``paddle.save`` / ``paddle.load`` on
  state_dicts (pickle container + tensor payloads).
- python/paddle/distributed/checkpoint/ — ``save_state_dict`` /
  ``load_state_dict`` with DistTensor metadata and cross-topology reshard
  on load.

TPU-native design (orbax/tensorstore pattern, hand-rolled so the format is
self-contained): a checkpoint is a directory; every array leaf becomes one
or more ``.npy`` shard files covering disjoint index-ranges of the global
array, described by a JSON metadata file.  Each host writes only the shards
it owns (``addressable_shards`` with ``replica_id == 0``), so saving a
sharded 70B state never gathers it to one host.  Loading reads only the
byte-ranges a target sharding needs, so a checkpoint written on one mesh
restores onto any other mesh shape ("reshard-on-load", which the elastic
path depends on — SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.spans import span as _span
from ..resilience import _state as _rs_state

__all__ = ["save", "load", "save_state_dict", "load_state_dict",
           "async_save", "AsyncCheckpointer", "latest_checkpoint",
           "verify_checkpoint", "CheckpointCorruptError"]

_META = "metadata.json"
# commit sentinel: last file rank 0 writes; a directory without it is a
# torn save and reads as incomplete (v2 checkpoints — see _is_complete)
_COMMIT = "COMMITTED"
_FORMAT = "paddle_tpu.ckpt.v2"


class CheckpointCorruptError(RuntimeError):
    """A shard file failed its recorded checksum (or is unreadable).

    Deliberately NOT in ``resilience.DEFAULT_RETRYABLE``: re-reading the
    same bytes cannot fix them.  The recovery path is fallback —
    ``latest_checkpoint(root, valid_only=True)`` skips the corrupt
    directory, and the resilience supervisor restarts onto the previous
    valid checkpoint (docs/RESILIENCE.md, "Recovering a torn
    checkpoint")."""


def _crc32_of(arr) -> int:
    """Checksum of an array's data bytes (C-order, layout-independent)."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _crc32_of_file(fpath: str) -> int:
    """Streaming checksum of a shard file's array bytes: mmap + fixed-size
    slices, so verifying a multi-GB shard costs O(chunk) resident memory
    instead of two full in-RAM copies.  Matches ``_crc32_of``'s C-order
    convention (non-C-contiguous saves fall back to the copying path)."""
    arr = np.load(fpath, mmap_mode="r")
    if not arr.flags.c_contiguous:
        return _crc32_of(np.asarray(arr))
    flat = arr.reshape(-1).view(np.uint8)
    crc = 0
    step = 16 << 20
    for off in range(0, flat.size, step):
        crc = zlib.crc32(flat[off:off + step], crc)
    return crc & 0xFFFFFFFF


def _fault(site: str) -> None:
    """Fault-injection site: one falsy check when disabled (the
    observability zero-overhead contract)."""
    fi = _rs_state.FAULTS[0]
    if fi is not None:
        fi(site)


# ---------------------------------------------------------------------------
# paddle.save / paddle.load parity (single-file, host-local)
# ---------------------------------------------------------------------------

def _to_host(obj):
    def leaf(x):
        if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            return {"__prng_key__": np.asarray(jax.random.key_data(x)),
                    "impl": str(jax.random.key_impl(x))}
        if isinstance(x, (jax.Array, jnp.ndarray)):
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(leaf, obj)


def _from_host(obj, to_device: bool):
    def leaf(x):
        if isinstance(x, dict) and "__prng_key__" in x:
            return jax.random.wrap_key_data(jnp.asarray(x["__prng_key__"]),
                                            impl=x["impl"])
        if to_device and isinstance(x, np.ndarray):
            # COPY, never zero-copy: jax CPU aliases host numpy buffers,
            # and a loaded state fed to a donating TrainStep would have
            # XLA free/overwrite memory numpy still owns (observed as a
            # segfault on the resume-after-preemption path)
            return jnp.array(x)
        return x
    return jax.tree_util.tree_map(leaf, obj,
                                  is_leaf=lambda x: isinstance(x, dict)
                                  and "__prng_key__" in x)


def save(obj: Any, path: str, protocol: int = 4, retry=None) -> None:
    """``paddle.save`` parity: pickle a (possibly nested) object, with array
    leaves materialised to host numpy.  ``retry`` (a
    ``resilience.RetryPolicy``) re-attempts a failed write."""
    # span: ckpt I/O is where jobs wedge on dead filesystems — the
    # span_begin breadcrumb makes that the last thing a hang dump shows
    with _span("ckpt.save", path=path):
        host = _to_host(obj)

        def write():
            _fault("ckpt.save")
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    pickle.dump(host, f, protocol=protocol)
                os.replace(tmp, path)  # atomic: no torn ckpt on preemption
            except BaseException:
                # a failed write must not litter .tmp debris that a later
                # save (or a directory scan) trips on
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        if retry is not None:
            retry.run(write, site="ckpt.save")
        else:
            write()


def load(path: str, return_numpy: bool = False, retry=None) -> Any:
    """``paddle.load`` parity: returns device arrays by default, matching the
    reference (``return_numpy=True`` keeps host numpy)."""
    with _span("ckpt.load", path=path):
        def read():
            _fault("ckpt.load")
            with open(path, "rb") as f:
                return pickle.load(f)

        obj = retry.run(read, site="ckpt.load") if retry is not None \
            else read()
        return _from_host(obj, to_device=not return_numpy)


# ---------------------------------------------------------------------------
# flat key <-> pytree
# ---------------------------------------------------------------------------

def _flatten(tree) -> Tuple[Dict[str, Any], Any]:
    """Flatten a pytree to {'a/b/0': leaf} using path names."""
    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree_util.tree_structure(tree)
    for path, leaf in leaves_with_path:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        flat["/".join(parts) if parts else "_root"] = leaf
    return flat, treedef


def _key_to_fname(key: str) -> str:
    # percent-escape so nested path 'a/b' and dotted key 'a.b' cannot collide
    return key.replace("%", "%25").replace("/", "%2F")


# ---------------------------------------------------------------------------
# distributed sharded save
# ---------------------------------------------------------------------------

def _snapshot_entries(state_dict: Any, materialize: bool):
    """Normalise a pytree into checkpoint entries, one per flat key:
    ``(key, "array", shape, dtype_name, [(ranges, data)], prng_impl)`` or
    ``(key, "obj", value)``.  ``materialize=True`` copies shard data to host
    numpy eagerly (required for async saving, where the arrays may be
    donated to the next step); otherwise ``data`` stays a lazy callable."""
    flat, _ = _flatten(state_dict)
    out = []
    for key, leaf in flat.items():
        prng_impl = None
        if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            prng_impl = str(jax.random.key_impl(leaf))
            leaf = jax.random.key_data(leaf)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shards = []
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # replicas: first owner writes
                idx = _index_to_ranges(shard.index, leaf.shape)
                data = (np.asarray(shard.data) if materialize
                        else (lambda s=shard: np.asarray(s.data)))
                shards.append((idx, data))
            out.append((key, "array", tuple(leaf.shape),
                        jnp.dtype(leaf.dtype).name, shards, prng_impl))
        elif isinstance(leaf, np.ndarray):
            out.append((key, "array", leaf.shape, leaf.dtype.name,
                        [(_full_ranges(leaf.shape), leaf)], None))
        else:
            out.append((key, "obj", leaf))
    return out


def _write_entries(entries, path: str, overwrite: bool = True) -> None:
    """The single writer of the v2 on-disk format (shard .npy files + a
    per-rank metadata JSON carrying per-file checksums + a rank-0 commit
    sentinel making the directory save atomic)."""
    _fault("ckpt.save")
    os.makedirs(path, exist_ok=True)
    # re-saving in place: drop the commit sentinel and rank 0's metadata
    # FIRST so the directory reads as incomplete (and is skipped by
    # latest_checkpoint) while shard files are being rewritten; both are
    # atomically re-created at the end
    if jax.process_index() == 0:
        for stale in (_COMMIT, _META):
            try:
                os.remove(os.path.join(path, stale))
            except FileNotFoundError:
                pass
    meta: Dict[str, Any] = {"format": _FORMAT,
                            "process_count": jax.process_count(),
                            "arrays": {}, "objects": {}}
    for item in entries:
        key = item[0]
        if item[1] == "obj":
            meta["objects"][key] = _jsonable(item[2])
            continue
        _, _, shape, dtype, shards, prng_impl = item
        entry: Dict[str, Any] = {"dtype": dtype, "shape": list(shape), "files": []}
        if prng_impl is not None:
            entry["prng_impl"] = prng_impl
        for idx, data in shards:
            fname = (f"{_key_to_fname(key)}"
                     f".{'_'.join(f'{a}-{b}' for a, b in idx) or 'scalar'}.npy")
            fpath = os.path.join(path, fname)
            fdesc: Dict[str, Any] = {"ranges": idx, "file": fname}
            if overwrite or not os.path.exists(fpath):
                arr = np.asarray(data() if callable(data) else data)
                try:
                    np.save(fpath, arr)
                except BaseException:
                    # a torn shard from a failed write must not survive:
                    # an overwrite=False retry would see the file, skip
                    # rewriting it, record no crc, and COMMIT a directory
                    # that verifies clean but cannot be read
                    try:
                        os.unlink(fpath)
                    except OSError:
                        pass
                    raise
                fdesc["crc32"] = _crc32_of(arr)
                fdesc["nbytes"] = int(arr.nbytes)
            else:
                # overwrite=False reuse: this save REPLACES the metadata,
                # so re-checksum the existing file — dropping the crc here
                # would silently disable corruption detection for every
                # reused shard.  An unreadable reused file stays un-crc'd
                # (the load will fail on it anyway).
                try:
                    fdesc["crc32"] = _crc32_of_file(fpath)
                    fdesc["nbytes"] = int(
                        np.load(fpath, mmap_mode="r").nbytes)
                except Exception:
                    pass
            entry["files"].append(fdesc)
        meta["arrays"][key] = entry
    # each process writes its own metadata file; rank 0's name is canonical
    # and load() unions them all (multi-host writes to a shared fs compose)
    rank = jax.process_index()
    mname = _META if rank == 0 else f"metadata.{rank}.json"
    _atomic_json(meta, os.path.join(path, mname))
    if rank == 0:
        # commit sentinel LAST: its presence means rank 0's save finished
        # (other ranks' metadata is checked separately by _is_complete)
        _atomic_json({"format": _FORMAT,
                      "process_count": jax.process_count()},
                     os.path.join(path, _COMMIT))


def _atomic_json(obj, dest: str) -> None:
    tmp = dest + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
        os.replace(tmp, dest)
    except BaseException:
        # no .tmp debris after a failed write (a fault mid-save must not
        # leave files a later overwrite=True save trips on)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_state_dict(state_dict: Any, path: str, overwrite: bool = True,
                    retry=None) -> None:
    """Write a sharded checkpoint directory for a pytree of arrays.

    Every process writes only the shards it owns (lazily, one host copy at a
    time), so no rank ever materialises the full state.  ``retry`` (a
    ``resilience.RetryPolicy``) re-attempts a failed write from scratch."""
    with _span("ckpt.save_state_dict", path=path):
        entries = _snapshot_entries(state_dict, materialize=False)
        if retry is not None:
            retry.run(_write_entries, entries, path, overwrite,
                      site="ckpt.save")
        else:
            _write_entries(entries, path, overwrite)


def _jsonable(x):
    if isinstance(x, (bool, int, float, str)) or x is None:
        return x
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    return {"__pickle__": pickle.dumps(x).hex()}


def _unjson(x):
    if isinstance(x, dict) and "__pickle__" in x:
        return pickle.loads(bytes.fromhex(x["__pickle__"]))
    return x


def _index_to_ranges(index, shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _full_ranges(shape):
    return [[0, d] for d in shape]


# ---------------------------------------------------------------------------
# load + reshard
# ---------------------------------------------------------------------------

def _meta_files(path: str) -> List[str]:
    return [f for f in os.listdir(path)
            if f == _META or (f.startswith("metadata.") and f.endswith(".json"))]


def _is_complete(path: str) -> bool:
    """True iff rank 0's metadata exists, every writer rank's metadata is
    present (a multi-host save is torn until the last rank finishes), and —
    for v2 checkpoints — the commit sentinel landed."""
    full = os.path.join(path, _META)
    if not os.path.exists(full):
        return False
    try:
        with open(full) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    if meta.get("format") == _FORMAT \
            and not os.path.exists(os.path.join(path, _COMMIT)):
        return False   # v2 without its sentinel: save died mid-write
    return len(_meta_files(path)) >= meta.get("process_count", 1)


def _load_meta(path: str) -> Dict[str, Any]:
    metas = _meta_files(path)
    if not metas:
        raise FileNotFoundError(f"no checkpoint metadata in {path}")
    # rank 0's metadata records how many writers this save had; ignore
    # higher-rank metadata files left over from an older, wider save
    expected = 1
    if _META in metas:
        with open(os.path.join(path, _META)) as f:
            expected = json.load(f).get("process_count", 1)
    merged: Dict[str, Any] = {"arrays": {}, "objects": {}}
    for m in sorted(metas):
        if m != _META:
            try:
                rank = int(m.split(".")[1])
            except (IndexError, ValueError):
                continue
            if rank >= expected:
                continue  # stale: from a previous save with more writers
        with open(os.path.join(path, m)) as f:
            meta = json.load(f)
        for k, v in meta.get("arrays", {}).items():
            if k in merged["arrays"]:
                merged["arrays"][k]["files"].extend(v["files"])
            else:
                merged["arrays"][k] = v
        merged["objects"].update(meta.get("objects", {}))
    return merged


class _ShardReader:
    """Reads an arbitrary index-window of one global array from its shard
    files (mmap'd, so only the needed bytes are touched).

    With ``verify=True`` (the default), every shard file that is actually
    read is checked once against the checksum the save recorded — a
    bit-flipped or truncated shard raises :class:`CheckpointCorruptError`
    instead of silently restoring garbage weights.  Verification reads
    the whole file (checksums are per-file); pass
    ``load_state_dict(..., verify=False)`` to keep window reads lazy on
    trusted storage."""

    def __init__(self, path: str, entry: Dict[str, Any],
                 verify: bool = True):
        self.path = path
        self.entry = entry
        self.shape = tuple(entry["shape"])
        self.dtype = np.dtype(entry["dtype"])
        self._verify = verify
        self._checked: set = set()

    def _check(self, fdesc) -> None:
        if not self._verify or "crc32" not in fdesc \
                or fdesc["file"] in self._checked:
            return
        fpath = os.path.join(self.path, fdesc["file"])
        try:
            crc = _crc32_of_file(fpath)
        except Exception as e:
            raise CheckpointCorruptError(
                f"unreadable shard file {fpath}: {e}") from e
        if crc != fdesc["crc32"]:
            raise CheckpointCorruptError(
                f"checksum mismatch in {fpath}: metadata records "
                f"{fdesc['crc32']:#010x}, file has {crc:#010x}")
        self._checked.add(fdesc["file"])

    def read(self, index: Tuple[slice, ...]) -> np.ndarray:
        want = _index_to_ranges(index, self.shape)
        out_shape = tuple(b - a for a, b in want)
        out = np.empty(out_shape, self.dtype)
        filled = 0
        seen = set()
        for fdesc in self.entry["files"]:
            if fdesc["file"] in seen:
                continue
            seen.add(fdesc["file"])
            ranges = fdesc["ranges"]
            inter = [(max(a, wa), min(b, wb))
                     for (a, b), (wa, wb) in zip(ranges, want)]
            if any(a >= b for a, b in inter) and out_shape != ():
                continue
            self._check(fdesc)
            fpath = os.path.join(self.path, fdesc["file"])
            try:
                src = np.load(fpath, mmap_mode="r")
            except Exception as e:
                # a truncated/garbled npy raises a plain ValueError from
                # numpy; type it so the supervisor's fallback path (pick
                # an older valid checkpoint) recognises the condition
                raise CheckpointCorruptError(
                    f"unreadable shard file {fpath}: {e}") from e
            if out_shape == ():
                # np.array (copy): never hand out a view of the read-only
                # mmap — jax zero-copies host arrays and a donated write
                # into PROT_READ pages is a SIGSEGV
                return np.array(src).reshape(())
            src_sel = tuple(slice(a - ra, b - ra)
                            for (a, b), (ra, _) in zip(inter, ranges))
            dst_sel = tuple(slice(a - wa, b - wa)
                            for (a, b), (wa, _) in zip(inter, want))
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != int(np.prod(out_shape)):
            raise ValueError(
                f"checkpoint shards do not cover requested window {want} "
                f"of array shape {self.shape} (covered {filled} elements)")
        return out


def load_state_dict(path: str, template: Any = None,
                    shardings: Optional[Dict[str, Any]] = None, *,
                    verify: bool = True, retry=None) -> Any:
    """Load a sharded checkpoint.

    - ``template=None``: returns a flat ``{key: np.ndarray}`` dict.
    - ``template`` a pytree: returns the same structure; any leaf carrying
      a ``.sharding`` (a ``jax.Array`` or an abstract
      ``jax.ShapeDtypeStruct``) is restored **with that sharding**
      (reshard-on-load: each device reads only its window).
    - ``shardings``: optional ``{key: jax.sharding.Sharding}`` overriding /
      supplementing the template's shardings.
    - ``verify``: check each shard file read against its recorded
      checksum (raises :class:`CheckpointCorruptError` on mismatch);
      ``False`` skips the integrity pass and keeps window reads lazy.
    - ``retry``: a ``resilience.RetryPolicy`` re-attempting transient
      read failures (corruption is NOT retried — fall back via
      ``latest_checkpoint(..., valid_only=True)`` instead).
    """
    with _span("ckpt.load_state_dict", path=path):
        if retry is not None:
            return retry.run(_load_state_dict, path, template, shardings,
                             verify, site="ckpt.load")
        return _load_state_dict(path, template, shardings, verify)


def _load_state_dict(path, template, shardings, verify=True):
    _fault("ckpt.load")
    meta = _load_meta(path)
    readers = {k: _ShardReader(path, e, verify=verify)
               for k, e in meta["arrays"].items()}

    def materialize(key: str, like=None):
        if key in readers:
            r = readers[key]
            prng_impl = meta["arrays"][key].get("prng_impl")
            shard = (shardings or {}).get(key)
            if shard is None and like is not None:
                # jax.Array AND abstract ShapeDtypeStruct templates both
                # carry .sharding — the supervisor restores through
                # buffer-free struct templates (donation-proof)
                shard = getattr(like, "sharding", None)
            if prng_impl is not None:
                # typed PRNG key: stored as raw uint32 key data; place the
                # raw data on the target sharding FIRST (device_put rejects
                # typed key arrays on multi-process shardings), then re-wrap
                data = r.read(tuple(slice(0, d) for d in r.shape))
                gdata = (jax.device_put(jnp.asarray(data), shard)
                         if shard is not None else jnp.asarray(data))
                return jax.random.wrap_key_data(gdata, impl=prng_impl)
            if shard is not None:
                return jax.make_array_from_callback(r.shape, shard, r.read)
            return r.read(tuple(slice(0, d) for d in r.shape))
        if key in meta["objects"]:
            return _unjson(meta["objects"][key])
        raise KeyError(f"key {key!r} not in checkpoint {path}")

    if template is None:
        out = {k: materialize(k) for k in readers}
        out.update({k: _unjson(v) for k, v in meta["objects"].items()})
        return out

    flat, treedef = _flatten(template)
    leaves = [materialize(k, like=v) for k, v in flat.items()]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def latest_checkpoint(root: str, prefix: str = "step_",
                      valid_only: bool = False) -> Optional[str]:
    """Return the highest-numbered ``{prefix}{N}`` checkpoint dir under root
    that finished writing (metadata from every writer rank + commit
    sentinel), for resume-after-preemption.

    ``valid_only=True`` additionally verifies data integrity
    (:func:`verify_checkpoint`: every shard file present and matching its
    recorded checksum) and **falls back**: a torn or corrupt newest
    directory is skipped in favor of the last *good* one, so resume never
    crashes on the checkpoint the failure tore."""
    if not os.path.isdir(root):
        return None
    candidates = []
    for name in os.listdir(root):
        if not name.startswith(prefix):
            continue
        try:
            n = int(name[len(prefix):])
        except ValueError:
            continue
        candidates.append((n, os.path.join(root, name)))
    for _n, full in sorted(candidates, reverse=True):
        if valid_only:
            if not verify_checkpoint(full):
                return full
        elif _is_complete(full):
            return full
    return None


def verify_checkpoint(root: str, *, data: bool = True) -> List[str]:
    """Integrity-check one checkpoint directory; returns a list of
    problems (empty == valid).

    Checks: completeness (every writer rank's metadata + the v2 commit
    sentinel), every referenced shard file present, and — with
    ``data=True`` — every shard file matching its recorded checksum.
    Never raises: a verdict on a half-deleted directory is still a
    verdict."""
    if not os.path.isdir(root):
        return [f"{root}: not a directory"]
    if not _is_complete(root):
        return [f"{root}: incomplete (missing metadata or commit sentinel)"]
    try:
        meta = _load_meta(root)
    except Exception as e:  # noqa: BLE001 — verdict, not crash
        return [f"{root}: unreadable metadata: {e}"]
    problems: List[str] = []
    seen = set()
    for key, entry in sorted(meta["arrays"].items()):
        for fdesc in entry["files"]:
            fname = fdesc["file"]
            if fname in seen:
                continue
            seen.add(fname)
            fpath = os.path.join(root, fname)
            if not os.path.exists(fpath):
                problems.append(f"{key}: missing shard file {fname}")
                continue
            if not data or "crc32" not in fdesc:
                continue
            try:
                crc = _crc32_of_file(fpath)
            except Exception as e:  # noqa: BLE001
                problems.append(f"{key}: unreadable shard {fname}: {e}")
                continue
            if crc != fdesc["crc32"]:
                problems.append(
                    f"{key}: checksum mismatch in {fname} (metadata "
                    f"{fdesc['crc32']:#010x}, file {crc:#010x})")
    return problems


# ---------------------------------------------------------------------------
# async save (reference: orbax AsyncCheckpointer pattern)
# ---------------------------------------------------------------------------

class AsyncCheckpointer:
    """Serialises saves onto a background thread so the train loop only
    blocks for the device→host copy of the *previous* save (if still
    running), never for disk IO.

    ``retry`` (a ``resilience.RetryPolicy``) re-attempts a failed
    background write before the error is surfaced; a background failure
    that exhausts it is re-raised from ``wait()`` — or from the *next*
    ``save()``, which waits first."""

    def __init__(self, retry=None):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._retry = retry

    def save(self, state_dict: Any, path: str) -> None:
        self.wait()
        # snapshot to host synchronously (cheap vs disk IO; arrays may be
        # donated/mutated by the next step otherwise), write in background
        entries = _snapshot_entries(state_dict, materialize=True)

        def run():
            try:
                # span from the writer thread: the begin breadcrumb marks
                # the write in flight, so a wedged background save is
                # attributed in a hang dump (its stack is there too)
                with _span("ckpt.async_save", path=path):
                    if self._retry is not None:
                        self._retry.run(_write_entries, entries, path,
                                        site="ckpt.save")
                    else:
                        _write_entries(entries, path)
            except BaseException as e:
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def async_save(state_dict: Any, path: str) -> AsyncCheckpointer:
    """One-shot async save; returns the checkpointer (call ``.wait()``)."""
    ckpt = AsyncCheckpointer()
    ckpt.save(state_dict, path)
    return ckpt


# orbax interop (ecosystem-format checkpoints) — lazy import; see orbax_io
def __getattr__(name):
    if name in ("save_orbax", "load_orbax", "async_save_orbax", "orbax_io"):
        import importlib
        mod = importlib.import_module(".orbax_io", __name__)
        globals()["orbax_io"] = mod
        if name == "orbax_io":
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module 'paddle_tpu.ckpt' has no attribute {name!r}")
