"""Checkpointing: ``save``/``load`` parity plus distributed sharded
checkpoints with reshard-on-load.

Reference surface (SURVEY.md §5.4):
- python/paddle/framework/io.py — ``paddle.save`` / ``paddle.load`` on
  state_dicts (pickle container + tensor payloads).
- python/paddle/distributed/checkpoint/ — ``save_state_dict`` /
  ``load_state_dict`` with DistTensor metadata and cross-topology reshard
  on load.

TPU-native design (orbax/tensorstore pattern, hand-rolled so the format is
self-contained): a checkpoint is a directory; every array leaf becomes one
or more ``.npy`` shard files covering disjoint index-ranges of the global
array, described by a JSON metadata file.  Each host writes only the shards
it owns (``addressable_shards`` with ``replica_id == 0``), so saving a
sharded 70B state never gathers it to one host.  Loading reads only the
byte-ranges a target sharding needs, so a checkpoint written on one mesh
restores onto any other mesh shape ("reshard-on-load", which the elastic
path depends on — SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import os
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.spans import span as _span

__all__ = ["save", "load", "save_state_dict", "load_state_dict",
           "async_save", "AsyncCheckpointer", "latest_checkpoint"]

_META = "metadata.json"


# ---------------------------------------------------------------------------
# paddle.save / paddle.load parity (single-file, host-local)
# ---------------------------------------------------------------------------

def _to_host(obj):
    def leaf(x):
        if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            return {"__prng_key__": np.asarray(jax.random.key_data(x)),
                    "impl": str(jax.random.key_impl(x))}
        if isinstance(x, (jax.Array, jnp.ndarray)):
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(leaf, obj)


def _from_host(obj, to_device: bool):
    def leaf(x):
        if isinstance(x, dict) and "__prng_key__" in x:
            return jax.random.wrap_key_data(jnp.asarray(x["__prng_key__"]),
                                            impl=x["impl"])
        if to_device and isinstance(x, np.ndarray):
            # COPY, never zero-copy: jax CPU aliases host numpy buffers,
            # and a loaded state fed to a donating TrainStep would have
            # XLA free/overwrite memory numpy still owns (observed as a
            # segfault on the resume-after-preemption path)
            return jnp.array(x)
        return x
    return jax.tree_util.tree_map(leaf, obj,
                                  is_leaf=lambda x: isinstance(x, dict)
                                  and "__prng_key__" in x)


def save(obj: Any, path: str, protocol: int = 4) -> None:
    """``paddle.save`` parity: pickle a (possibly nested) object, with array
    leaves materialised to host numpy."""
    # span: ckpt I/O is where jobs wedge on dead filesystems — the
    # span_begin breadcrumb makes that the last thing a hang dump shows
    with _span("ckpt.save", path=path):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(_to_host(obj), f, protocol=protocol)
        os.replace(tmp, path)  # atomic: no torn checkpoint on preemption


def load(path: str, return_numpy: bool = False) -> Any:
    """``paddle.load`` parity: returns device arrays by default, matching the
    reference (``return_numpy=True`` keeps host numpy)."""
    with _span("ckpt.load", path=path):
        with open(path, "rb") as f:
            obj = pickle.load(f)
        return _from_host(obj, to_device=not return_numpy)


# ---------------------------------------------------------------------------
# flat key <-> pytree
# ---------------------------------------------------------------------------

def _flatten(tree) -> Tuple[Dict[str, Any], Any]:
    """Flatten a pytree to {'a/b/0': leaf} using path names."""
    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree_util.tree_structure(tree)
    for path, leaf in leaves_with_path:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        flat["/".join(parts) if parts else "_root"] = leaf
    return flat, treedef


def _key_to_fname(key: str) -> str:
    # percent-escape so nested path 'a/b' and dotted key 'a.b' cannot collide
    return key.replace("%", "%25").replace("/", "%2F")


# ---------------------------------------------------------------------------
# distributed sharded save
# ---------------------------------------------------------------------------

def _snapshot_entries(state_dict: Any, materialize: bool):
    """Normalise a pytree into checkpoint entries, one per flat key:
    ``(key, "array", shape, dtype_name, [(ranges, data)], prng_impl)`` or
    ``(key, "obj", value)``.  ``materialize=True`` copies shard data to host
    numpy eagerly (required for async saving, where the arrays may be
    donated to the next step); otherwise ``data`` stays a lazy callable."""
    flat, _ = _flatten(state_dict)
    out = []
    for key, leaf in flat.items():
        prng_impl = None
        if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            prng_impl = str(jax.random.key_impl(leaf))
            leaf = jax.random.key_data(leaf)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shards = []
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # replicas: first owner writes
                idx = _index_to_ranges(shard.index, leaf.shape)
                data = (np.asarray(shard.data) if materialize
                        else (lambda s=shard: np.asarray(s.data)))
                shards.append((idx, data))
            out.append((key, "array", tuple(leaf.shape),
                        jnp.dtype(leaf.dtype).name, shards, prng_impl))
        elif isinstance(leaf, np.ndarray):
            out.append((key, "array", leaf.shape, leaf.dtype.name,
                        [(_full_ranges(leaf.shape), leaf)], None))
        else:
            out.append((key, "obj", leaf))
    return out


def _write_entries(entries, path: str, overwrite: bool = True) -> None:
    """The single writer of the v1 on-disk format (shard .npy files + a
    per-rank metadata JSON)."""
    os.makedirs(path, exist_ok=True)
    # re-saving in place: drop rank 0's metadata FIRST so the directory reads
    # as incomplete (and is skipped by latest_checkpoint) while shard files
    # are being rewritten; it is atomically re-created at the end
    if jax.process_index() == 0:
        try:
            os.remove(os.path.join(path, _META))
        except FileNotFoundError:
            pass
    meta: Dict[str, Any] = {"format": "paddle_tpu.ckpt.v1",
                            "process_count": jax.process_count(),
                            "arrays": {}, "objects": {}}
    for item in entries:
        key = item[0]
        if item[1] == "obj":
            meta["objects"][key] = _jsonable(item[2])
            continue
        _, _, shape, dtype, shards, prng_impl = item
        entry: Dict[str, Any] = {"dtype": dtype, "shape": list(shape), "files": []}
        if prng_impl is not None:
            entry["prng_impl"] = prng_impl
        for idx, data in shards:
            fname = (f"{_key_to_fname(key)}"
                     f".{'_'.join(f'{a}-{b}' for a, b in idx) or 'scalar'}.npy")
            fpath = os.path.join(path, fname)
            if overwrite or not os.path.exists(fpath):
                np.save(fpath, data() if callable(data) else data)
            entry["files"].append({"ranges": idx, "file": fname})
        meta["arrays"][key] = entry
    # each process writes its own metadata file; rank 0's name is canonical
    # and load() unions them all (multi-host writes to a shared fs compose)
    rank = jax.process_index()
    mname = _META if rank == 0 else f"metadata.{rank}.json"
    tmp = os.path.join(path, mname + f".tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp, os.path.join(path, mname))


def save_state_dict(state_dict: Any, path: str, overwrite: bool = True) -> None:
    """Write a sharded checkpoint directory for a pytree of arrays.

    Every process writes only the shards it owns (lazily, one host copy at a
    time), so no rank ever materialises the full state."""
    with _span("ckpt.save_state_dict", path=path):
        _write_entries(_snapshot_entries(state_dict, materialize=False),
                       path, overwrite=overwrite)


def _jsonable(x):
    if isinstance(x, (bool, int, float, str)) or x is None:
        return x
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    return {"__pickle__": pickle.dumps(x).hex()}


def _unjson(x):
    if isinstance(x, dict) and "__pickle__" in x:
        return pickle.loads(bytes.fromhex(x["__pickle__"]))
    return x


def _index_to_ranges(index, shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _full_ranges(shape):
    return [[0, d] for d in shape]


# ---------------------------------------------------------------------------
# load + reshard
# ---------------------------------------------------------------------------

def _meta_files(path: str) -> List[str]:
    return [f for f in os.listdir(path)
            if f == _META or (f.startswith("metadata.") and f.endswith(".json"))]


def _is_complete(path: str) -> bool:
    """True iff rank 0's metadata exists AND every writer rank's metadata is
    present (a multi-host save is torn until the last rank finishes)."""
    full = os.path.join(path, _META)
    if not os.path.exists(full):
        return False
    try:
        with open(full) as f:
            expected = json.load(f).get("process_count", 1)
    except (OSError, json.JSONDecodeError):
        return False
    return len(_meta_files(path)) >= expected


def _load_meta(path: str) -> Dict[str, Any]:
    metas = _meta_files(path)
    if not metas:
        raise FileNotFoundError(f"no checkpoint metadata in {path}")
    # rank 0's metadata records how many writers this save had; ignore
    # higher-rank metadata files left over from an older, wider save
    expected = 1
    if _META in metas:
        with open(os.path.join(path, _META)) as f:
            expected = json.load(f).get("process_count", 1)
    merged: Dict[str, Any] = {"arrays": {}, "objects": {}}
    for m in sorted(metas):
        if m != _META:
            try:
                rank = int(m.split(".")[1])
            except (IndexError, ValueError):
                continue
            if rank >= expected:
                continue  # stale: from a previous save with more writers
        with open(os.path.join(path, m)) as f:
            meta = json.load(f)
        for k, v in meta.get("arrays", {}).items():
            if k in merged["arrays"]:
                merged["arrays"][k]["files"].extend(v["files"])
            else:
                merged["arrays"][k] = v
        merged["objects"].update(meta.get("objects", {}))
    return merged


class _ShardReader:
    """Reads an arbitrary index-window of one global array from its shard
    files (mmap'd, so only the needed bytes are touched)."""

    def __init__(self, path: str, entry: Dict[str, Any]):
        self.path = path
        self.entry = entry
        self.shape = tuple(entry["shape"])
        self.dtype = np.dtype(entry["dtype"])

    def read(self, index: Tuple[slice, ...]) -> np.ndarray:
        want = _index_to_ranges(index, self.shape)
        out_shape = tuple(b - a for a, b in want)
        out = np.empty(out_shape, self.dtype)
        filled = 0
        seen = set()
        for fdesc in self.entry["files"]:
            if fdesc["file"] in seen:
                continue
            seen.add(fdesc["file"])
            ranges = fdesc["ranges"]
            inter = [(max(a, wa), min(b, wb))
                     for (a, b), (wa, wb) in zip(ranges, want)]
            if any(a >= b for a, b in inter) and out_shape != ():
                continue
            src = np.load(os.path.join(self.path, fdesc["file"]), mmap_mode="r")
            if out_shape == ():
                # np.array (copy): never hand out a view of the read-only
                # mmap — jax zero-copies host arrays and a donated write
                # into PROT_READ pages is a SIGSEGV
                return np.array(src).reshape(())
            src_sel = tuple(slice(a - ra, b - ra)
                            for (a, b), (ra, _) in zip(inter, ranges))
            dst_sel = tuple(slice(a - wa, b - wa)
                            for (a, b), (wa, _) in zip(inter, want))
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != int(np.prod(out_shape)):
            raise ValueError(
                f"checkpoint shards do not cover requested window {want} "
                f"of array shape {self.shape} (covered {filled} elements)")
        return out


def load_state_dict(path: str, template: Any = None,
                    shardings: Optional[Dict[str, Any]] = None) -> Any:
    """Load a sharded checkpoint.

    - ``template=None``: returns a flat ``{key: np.ndarray}`` dict.
    - ``template`` a pytree: returns the same structure; any ``jax.Array``
      leaf in the template is restored **with the template's sharding**
      (reshard-on-load: each device reads only its window).
    - ``shardings``: optional ``{key: jax.sharding.Sharding}`` overriding /
      supplementing the template's shardings.
    """
    with _span("ckpt.load_state_dict", path=path):
        return _load_state_dict(path, template, shardings)


def _load_state_dict(path, template, shardings):
    meta = _load_meta(path)
    readers = {k: _ShardReader(path, e) for k, e in meta["arrays"].items()}

    def materialize(key: str, like=None):
        if key in readers:
            r = readers[key]
            prng_impl = meta["arrays"][key].get("prng_impl")
            shard = (shardings or {}).get(key)
            if shard is None and isinstance(like, jax.Array) and hasattr(like, "sharding"):
                shard = like.sharding
            if prng_impl is not None:
                # typed PRNG key: stored as raw uint32 key data; place the
                # raw data on the target sharding FIRST (device_put rejects
                # typed key arrays on multi-process shardings), then re-wrap
                data = r.read(tuple(slice(0, d) for d in r.shape))
                gdata = (jax.device_put(jnp.asarray(data), shard)
                         if shard is not None else jnp.asarray(data))
                return jax.random.wrap_key_data(gdata, impl=prng_impl)
            if shard is not None:
                return jax.make_array_from_callback(r.shape, shard, r.read)
            return r.read(tuple(slice(0, d) for d in r.shape))
        if key in meta["objects"]:
            return _unjson(meta["objects"][key])
        raise KeyError(f"key {key!r} not in checkpoint {path}")

    if template is None:
        out = {k: materialize(k) for k in readers}
        out.update({k: _unjson(v) for k, v in meta["objects"].items()})
        return out

    flat, treedef = _flatten(template)
    leaves = [materialize(k, like=v) for k, v in flat.items()]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def latest_checkpoint(root: str, prefix: str = "step_") -> Optional[str]:
    """Return the highest-numbered ``{prefix}{N}`` checkpoint dir under root
    that finished writing (metadata from every writer rank), for
    resume-after-preemption."""
    if not os.path.isdir(root):
        return None
    best, best_n = None, -1
    for name in os.listdir(root):
        if not name.startswith(prefix):
            continue
        try:
            n = int(name[len(prefix):])
        except ValueError:
            continue
        full = os.path.join(root, name)
        if n > best_n and _is_complete(full):
            best, best_n = full, n
    return best


# ---------------------------------------------------------------------------
# async save (reference: orbax AsyncCheckpointer pattern)
# ---------------------------------------------------------------------------

class AsyncCheckpointer:
    """Serialises saves onto a background thread so the train loop only
    blocks for the device→host copy of the *previous* save (if still
    running), never for disk IO."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, state_dict: Any, path: str) -> None:
        self.wait()
        # snapshot to host synchronously (cheap vs disk IO; arrays may be
        # donated/mutated by the next step otherwise), write in background
        entries = _snapshot_entries(state_dict, materialize=True)

        def run():
            try:
                # span from the writer thread: the begin breadcrumb marks
                # the write in flight, so a wedged background save is
                # attributed in a hang dump (its stack is there too)
                with _span("ckpt.async_save", path=path):
                    _write_entries(entries, path)
            except BaseException as e:
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def async_save(state_dict: Any, path: str) -> AsyncCheckpointer:
    """One-shot async save; returns the checkpointer (call ``.wait()``)."""
    ckpt = AsyncCheckpointer()
    ckpt.save(state_dict, path)
    return ckpt


# orbax interop (ecosystem-format checkpoints) — lazy import; see orbax_io
def __getattr__(name):
    if name in ("save_orbax", "load_orbax", "async_save_orbax", "orbax_io"):
        import importlib
        mod = importlib.import_module(".orbax_io", __name__)
        globals()["orbax_io"] = mod
        if name == "orbax_io":
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module 'paddle_tpu.ckpt' has no attribute {name!r}")
