"""Orbax interop: read/write checkpoints in the TPU-ecosystem format.

Reference capability: paddle's checkpoint files interoperate with its
ecosystem tooling; on TPU the ecosystem standard is orbax
(tensorstore-backed sharded arrays, async write). This adapter maps the
framework's state_dicts (flat name→array, possibly nested train states)
to orbax PyTree checkpoints, so paddle_tpu training can resume from or
hand off to maxtext/flax-style pipelines.

The native format (``paddle_tpu.ckpt.save/load``) remains the default —
it carries reshard-on-load metadata orbax does not; use orbax_io at the
ecosystem boundary.
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_orbax", "load_orbax", "async_save_orbax"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_orbax(path: str, state: Any) -> None:
    """Write ``state`` (any pytree of arrays) as an orbax checkpoint."""
    path = os.path.abspath(path)
    _checkpointer().save(path, state, force=True)


def load_orbax(path: str, template: Optional[Any] = None) -> Any:
    """Read an orbax checkpoint. ``template`` (matching pytree of arrays
    or ShapeDtypeStructs) restores placement/dtype; without it arrays
    come back as numpy."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    if template is None:
        return _checkpointer().restore(path)
    return _checkpointer().restore(
        path, restore_args=ocp.checkpoint_utils.construct_restore_args(
            template))


def async_save_orbax(path: str, state: Any):
    """Async write (reference: our ckpt.async_save); returns an object
    with ``wait_until_finished()``."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, state, force=True)
    return ckptr
