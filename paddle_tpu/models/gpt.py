"""GPT / ERNIE-style decoder family (BASELINE.json configs[1]: 13B TP+PP).

Reference capability: PaddleNLP's GPT-3 / ERNIE models trained with fleet
hybrid parallel on the reference core (SURVEY §0 scope note; fleet layers
§2.5). Differences from the Llama family that make this a distinct
architecture (matching the GPT/ERNIE lineage): learned absolute position
embeddings (no RoPE), full multi-head attention (no GQA), LayerNorm (not
RMSNorm) with biases, GELU 4h FFN, optional embedding dropout.

TPU-first: same mesh-axis design as llama.py — ColumnParallel/RowParallel
("mp"), Megatron-SP, pipeline stages via StackedPipelineStages ("pp"),
recompute, vocab-parallel CE — all inside one jit program.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, ParamAttr
from ..nn.layers_common import Dropout, Embedding, LayerList, LayerNorm
from ..distributed.mp_layers import (ColumnParallelLinear,
                                     ParallelCrossEntropy,
                                     RowParallelLinear,
                                     VocabParallelEmbedding, constrain)
from ..distributed.recompute import RecomputeWrapper
from .generation import CachedGenerationMixin


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    intermediate_size: Optional[int] = None      # default 4h
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_recompute: bool = False
    recompute_policy: Optional[str] = None
    recompute_num_layers: Optional[int] = None  # Megatron-style partial remat
    sequence_parallel: bool = False
    pipeline_stages: int = 1
    num_microbatches: Optional[int] = None
    virtual_pp_degree: int = 1
    # fused-kernel library (docs/KERNELS.md): GPT's qkv is already one
    # matmul and its norm is LayerNorm (no fused-rms op applies), so the
    # flag routes the 4h GELU FFN through incubate.fused_gelu_mlp — the
    # Pallas fused-MLP kernel on TPU, the same-numerics XLA composition
    # elsewhere.  "auto" fuses only where a kernel will serve.
    fused_ops: str = "auto"
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


PRESETS = {
    # GPT-3 ladder (PaddleNLP gpt3 configs)
    "gpt2-345m": GPTConfig(),
    "gpt3-1.3b": GPTConfig(hidden_size=2048, num_hidden_layers=24,
                           num_attention_heads=32,
                           max_position_embeddings=2048),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_hidden_layers=32,
                           num_attention_heads=32,
                           max_position_embeddings=2048),
    # BASELINE configs[1]: 13B decoder for TP+PP
    "gpt3-13b": GPTConfig(hidden_size=5120, num_hidden_layers=40,
                          num_attention_heads=40,
                          max_position_embeddings=2048),
    # ERNIE-style base (ernie-3.0 dense decoder shape)
    "ernie-base": GPTConfig(vocab_size=40000, hidden_size=768,
                            num_hidden_layers=12, num_attention_heads=12,
                            max_position_embeddings=2048),
    "tiny": GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=128),
}


def _attr(cfg: GPTConfig) -> ParamAttr:
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class GPTAttention(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        sp = cfg.sequence_parallel
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             weight_attr=_attr(cfg),
                                             sequence_parallel=sp)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          weight_attr=_attr(cfg),
                                          sequence_parallel=sp)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, x, attn_mask=None, cache=None, seq_lens=None,
                block_tables=None, span_starts=None, lora=None):
        cfg = self.cfg
        b, s = x.shape[:2]
        # multi-LoRA serving (docs/SERVING.md "Multi-LoRA"): per-slot
        # adapter deltas on the packed qkv projection and on out_proj —
        # x here is already ln_1-normed, exactly the projections' input
        from ..incubate.nn.functional import lora_delta

        def _out(t):
            y = self.out_proj(t)
            d = lora_delta(lora, t, "attn.out_proj")
            return y if d is None else y + d

        qkv = self.qkv_proj(x)
        dqkv = lora_delta(lora, x, "attn.qkv_proj")
        if dqkv is not None:
            qkv = qkv + dqkv
        qkv = qkv.reshape(b, s, 3, cfg.num_attention_heads,
                          cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = constrain(q, ("dp", "sharding"), None, "mp", None)
        k = constrain(k, ("dp", "sharding"), None, "mp", None)
        v = constrain(v, ("dp", "sharding"), None, "mp", None)
        if cache is not None and block_tables is not None:
            # paged KV pools (serving.Engine) — see LlamaAttention
            from ..incubate.nn.functional import (paged_decode_attend,
                                                  paged_prefill_write,
                                                  ragged_paged_attend)
            if span_starts is not None:
                # unified ragged step — see LlamaAttention
                out, new_cache = ragged_paged_attend(
                    cache, q, k, v, block_tables, span_starts, seq_lens)
                out = out.reshape(b, s, cfg.hidden_size)
                return self.dropout(_out(out)), new_cache
            if s == 1 and seq_lens is not None:
                out, new_cache = paged_decode_attend(
                    cache, q[:, 0], k[:, 0], v[:, 0], block_tables,
                    seq_lens)
                out = out[:, None].reshape(b, s, cfg.hidden_size)
                return self.dropout(_out(out)), new_cache
            plens = seq_lens if seq_lens is not None else \
                jnp.full((b,), s, jnp.int32)
            new_cache = paged_prefill_write(cache, k, v, block_tables,
                                            plens)
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=cfg.attention_dropout, training=self.training)
            out = out.reshape(b, s, cfg.hidden_size)
            return self.dropout(_out(out)), new_cache
        if cache is not None and s == 1 and seq_lens is not None:
            # single-token decode against the dense (or int8-quantized
            # 4-tuple) KV cache — shared cache-arity dispatch
            from ..incubate.nn.functional import decode_attend_cache
            out, new_cache = decode_attend_cache(
                cache, q[:, 0], k[:, 0], v[:, 0], seq_lens)
            out = out[:, None].reshape(b, s, cfg.hidden_size)
            return self.dropout(_out(out)), new_cache
        if cache is not None:
            from ..incubate.nn.functional import prefill_write_cache
            new_cache = prefill_write_cache(cache, k, v)
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=cfg.attention_dropout, training=self.training)
            out = out.reshape(b, s, cfg.hidden_size)
            return self.dropout(_out(out)), new_cache
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
            dropout_p=cfg.attention_dropout, training=self.training)
        out = out.reshape(b, s, cfg.hidden_size)
        return self.dropout(_out(out))


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        sp = cfg.sequence_parallel
        self.fc_in = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_size,
                                          has_bias=True,
                                          weight_attr=_attr(cfg),
                                          sequence_parallel=sp)
        self.fc_out = RowParallelLinear(cfg.ffn_size, cfg.hidden_size,
                                        has_bias=True,
                                        weight_attr=_attr(cfg),
                                        sequence_parallel=sp)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, x, lora=None):
        cfg = self.cfg
        from .llama import _use_fused
        from ..ops.tuning import geom_key

        if lora is not None:
            # multi-LoRA: the fc_out delta needs the GELU intermediate,
            # so the LoRA path pins the unfused FFN composition
            from ..incubate.nn.functional import lora_delta

            h1 = self.fc_in(x)
            d1 = lora_delta(lora, x, "mlp.fc_in")
            if d1 is not None:
                h1 = h1 + d1
            h = F.gelu(h1)
            y = self.fc_out(h)
            d2 = lora_delta(lora, h, "mlp.fc_out")
            return self.dropout(y if d2 is None else y + d2)

        def _kernel_serves():
            from ..ops.pallas import fused_mlp as _fm
            return _fm.supported(x.reshape(-1, cfg.hidden_size),
                                 self.fc_in.weight, self.fc_out.weight,
                                 op="fused_gelu_mlp")

        if _use_fused(cfg, "fused_gelu_mlp",
                      geom_key(h=cfg.hidden_size, i=cfg.ffn_size),
                      probe=_kernel_serves,
                      layers=(self.fc_in, self.fc_out)):
            # one pass over the FFN weights (incubate fused entry —
            # Pallas kernel on TPU, XLA composition elsewhere)
            from ..incubate.nn.functional import fused_gelu_mlp
            lead = x.shape[:-1]
            y = fused_gelu_mlp(x.reshape(-1, cfg.hidden_size),
                               self.fc_in.weight, self.fc_in.bias,
                               self.fc_out.weight, self.fc_out.bias)
            return self.dropout(y.reshape(*lead, cfg.hidden_size))
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTDecoderLayer(Layer):
    returns_aux = False
    supports_cache = True
    supports_paged = True   # paged-pool serving path (serving.Engine)

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = GPTAttention(cfg)
        self.ln_2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlp = GPTMLP(cfg)

    def forward(self, x, attn_mask=None, cache=None, seq_lens=None,
                block_tables=None, span_starts=None, lora=None):
        if cache is not None:
            attn, cache = self.attn(self.ln_1(x), attn_mask, cache=cache,
                                    seq_lens=seq_lens,
                                    block_tables=block_tables,
                                    span_starts=span_starts, lora=lora)
            x = x + attn
            x = x + self.mlp(self.ln_2(x), lora=lora)
            return x, cache
        x = x + self.attn(self.ln_1(x), attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(Layer):
    decoder_layer_cls: type = GPTDecoderLayer

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                   cfg.hidden_size)
        # position table is small → replicated plain embedding (the token
        # table is the one worth vocab-sharding)
        self.embed_positions = Embedding(cfg.max_position_embeddings,
                                         cfg.hidden_size,
                                         weight_attr=_attr(cfg))
        self.embed_dropout = Dropout(cfg.hidden_dropout)
        if cfg.recompute_num_layers is not None and not (
                0 < cfg.recompute_num_layers <= cfg.num_hidden_layers):
            raise ValueError(
                f"recompute_num_layers={cfg.recompute_num_layers} must "
                f"be in [1, num_hidden_layers={cfg.num_hidden_layers}]")
        if cfg.recompute_num_layers is not None and not cfg.use_recompute \
                and cfg.pipeline_stages <= 1:
            # ADVICE r5: the partial-remat count only takes effect under
            # use_recompute=True — say so instead of silently ignoring it
            # (under pipeline the combination is rejected outright below)
            warnings.warn(
                f"recompute_num_layers={cfg.recompute_num_layers} is "
                "ignored because use_recompute=False — set "
                "use_recompute=True to remat the first N layers",
                UserWarning, stacklevel=2)
        if cfg.pipeline_stages > 1:
            if cfg.recompute_num_layers is not None:
                raise NotImplementedError(
                    "recompute_num_layers applies per stacked layer; the "
                    "pp-scanned body remats uniformly — drop "
                    "recompute_num_layers under pipeline_stages > 1")
            from ..distributed.pipeline import StackedPipelineStages
            self.h = StackedPipelineStages(
                lambda: GPTDecoderLayer(cfg), cfg.num_hidden_layers,
                num_stages=cfg.pipeline_stages,
                num_microbatches=cfg.num_microbatches,
                num_virtual_pipeline_stages=cfg.virtual_pp_degree,
                use_recompute=cfg.use_recompute,
                recompute_policy=cfg.recompute_policy,
                extra_is_batched=(True,),
                has_aux=False)
        else:
            layers = []
            for i in range(cfg.num_hidden_layers):
                layer = GPTDecoderLayer(cfg)
                # partial remat (Megatron --recompute-num-layers): only
                # the first N layers re-run in backward
                if cfg.use_recompute and (
                        cfg.recompute_num_layers is None
                        or i < cfg.recompute_num_layers):
                    layer = RecomputeWrapper(layer,
                                             policy=cfg.recompute_policy)
                layers.append(layer)
            self.h = LayerList(layers)
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def init_cache(self, batch, max_len, dtype=None):
        """Per-layer dense (k, v) caches for cached generation."""
        cfg = self.cfg
        if cfg.pipeline_stages > 1:
            raise NotImplementedError(
                "cached generation requires pipeline_stages == 1")
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings} (learned positions)")
        from .generation import make_dense_caches
        return make_dense_caches(
            cfg.num_hidden_layers, batch, max_len,
            cfg.num_attention_heads, cfg.head_dim,
            dtype if dtype is not None else cfg.dtype)

    def _forward_cached(self, input_ids, caches, seq_lens,
                        block_tables=None, span_starts=None, lora=None):
        """Prefill (seq_lens None) or one-token decode against the caches.
        With ``block_tables`` the caches are paged pools (serving path);
        prefill then takes ``seq_lens`` as the real prompt lengths.  With
        ``span_starts`` the batch is the unified RAGGED serving step
        (chunked prefill + decode spans, ``seq_lens`` = span lengths).
        ``lora`` is the multi-LoRA pair (per-layer adapter packs,
        per-slot adapter ids).  Returns (hidden, new_caches)."""
        b, s = input_ids.shape
        decode = (s == 1 and seq_lens is not None)
        if span_starts is not None:
            pos = span_starts[:, None] + jnp.arange(s)[None, :]
        elif decode:
            pos = seq_lens[:, None]
        else:
            pos = jnp.arange(s)[None, :]
        x = self.embed_tokens(input_ids) + self.embed_positions(pos)
        x = self.embed_dropout(x)
        kw = {} if block_tables is None else {"block_tables": block_tables}
        if span_starts is not None:
            kw["span_starts"] = span_starts
        lens_arg = seq_lens if (decode or block_tables is not None) \
            else None
        lit = iter(lora[0]) if lora is not None else None
        laids = lora[1] if lora is not None else None
        from .generation import run_cached_layers
        x, new_caches = run_cached_layers(
            self.h, x, caches,
            lambda inner, x, cache: inner(
                x, cache=cache, seq_lens=lens_arg,
                lora=None if lit is None else (next(lit), laids), **kw))
        return self.ln_f(x), new_caches

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                caches=None, seq_lens=None, block_tables=None,
                span_starts=None, lora=None):
        cfg = self.cfg
        if caches is not None:
            if attn_mask is not None or position_ids is not None:
                raise NotImplementedError(
                    "cached forward supports dense causal prefill/decode "
                    "only — attn_mask/position_ids would be silently "
                    "ignored")
            return self._forward_cached(input_ids, caches, seq_lens,
                                        block_tables, span_starts, lora)
        if input_ids.shape[1] > cfg.max_position_embeddings:
            # learned absolute positions: jax's OOB gather would silently
            # clamp every index past the table to its last row
            raise ValueError(
                f"sequence length {input_ids.shape[1]} exceeds "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[1])[None, :]
        x = (self.embed_tokens(input_ids)
             + self.embed_positions(position_ids))
        x = self.embed_dropout(x)
        if cfg.pipeline_stages > 1:
            x = self.h(x, attn_mask)
        else:
            for layer in self.h:
                x = layer(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(CachedGenerationMixin, Layer):
    def _cache_supported(self) -> bool:
        return self.cfg.pipeline_stages == 1

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.model = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(cfg.hidden_size,
                                                cfg.vocab_size,
                                                has_bias=False,
                                                weight_attr=_attr(cfg))
        self.loss_fn = ParallelCrossEntropy(ignore_index=-100)

    def logits(self, hidden):
        if self.cfg.tie_word_embeddings:
            w = self.model.embed_tokens.weight
            logits = hidden @ w.T
            return constrain(logits, ("dp", "sharding"), None, "mp")
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None,
                position_ids=None):
        hidden = self.model(input_ids, attn_mask, position_ids)
        logits = self.logits(hidden)
        if labels is None:
            return logits
        loss = self.loss_fn(logits.astype(jnp.float32), labels)
        valid = (labels != -100)
        return jnp.sum(loss * valid) / jnp.maximum(jnp.sum(valid), 1)

def gpt(name_or_config="tiny", **overrides) -> GPTForCausalLM:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return GPTForCausalLM(cfg)
